"""Synthetic sky models + systematic-error Jones solutions.

In-framework replacement for the reference's file-based simulators:
``calibration/simulate.py`` (simulate_models: sky0/sky/cluster/rho text files
+ ``.S.solutions``) and the sky/solution part of
``calibration/generate_data.py:896-1237`` (simulate_data).  Instead of
writing text files for external SAGECal binaries, everything is built as
struct-of-arrays (cal/coherency.SkyArrays) consumed directly by the JAX
prediction + solver path; cal/skyio can still round-trip the reference file
formats at the data edge.

All draws are host-side numpy from a seeded Generator — simulation setup is
once-per-episode host work; the heavy math (prediction, solve, influence)
stays on device.
"""

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from smartcal_tpu.cal import observation as obs_mod
from smartcal_tpu.cal.coherency import SkyArrays

TWO_PI = 2.0 * math.pi


def _rng_of(key, salt=0):
    return obs_mod.host_rng(key, salt)


def _powerlaw_flux(rng, n, a, b, alpha=-2.0):
    """Fluxes with dN/dS ~ S^alpha in [a, b] (reference simulate.py:106-121)."""
    nn = rng.random(n)
    ap, bp = a ** (alpha + 1), b ** (alpha + 1)
    return (ap + nn * (bp - ap)) ** (1.0 / (alpha + 1))


class SkyDraw:
    """Accumulator for struct-of-arrays sky construction."""

    def __init__(self):
        self.l, self.m, self.flux, self.sp = [], [], [], []
        self.gauss, self.is_gauss, self.cluster = [], [], []

    def add(self, l, m, flux, sp, cluster, gauss=None):
        l, m, flux = map(np.atleast_1d, (l, m, flux))
        n = l.shape[0]
        sp = np.broadcast_to(np.atleast_1d(sp), (n,))
        self.l.append(l)
        self.m.append(m)
        self.flux.append(flux)
        self.sp.append(sp)
        if gauss is None:
            self.gauss.append(np.zeros((n, 3)))
            self.is_gauss.append(np.zeros(n, bool))
        else:
            self.gauss.append(np.broadcast_to(gauss, (n, 3)))
            self.is_gauss.append(np.ones(n, bool))
        self.cluster.append(np.full(n, cluster, np.int32))

    def build(self, n_clusters, f0):
        l = np.concatenate(self.l)
        m = np.concatenate(self.m)
        n = np.sqrt(np.maximum(1.0 - l * l - m * m, 0.0)) - 1.0
        flux = np.concatenate(self.flux)
        sp = np.concatenate(self.sp)
        fc = np.stack([np.log(np.maximum(flux, 1e-12)), sp,
                       np.zeros_like(sp), np.zeros_like(sp)], axis=-1)
        return SkyArrays(
            lmn=np.stack([l, m, n], axis=-1), flux_coef=fc,
            f0=np.full_like(flux, f0), gauss=np.concatenate(self.gauss),
            is_gauss=np.concatenate(self.is_gauss),
            cluster=np.concatenate(self.cluster), n_clusters=n_clusters)


class CalibModels(NamedTuple):
    """Output of :func:`simulate_models` (reference simulate.py return +
    the files it wrote, as arrays).

    sky_sim   : SkyArrays, K+1 clusters (K calibrated + weak background)
    sky_cal   : SkyArrays, K clusters (outlier fluxes /100, as the
                reference's calibration sky — beam-attenuation stand-in)
    sky_table : (K, 5) float32 rows [cluster_id, l, m, sI, sP] (skylmn.txt)
    rho       : (K,) spectral ADMM rho (analytic init, flux-proportional)
    rho_spatial : (K,) spatial ADMM rho
    lm_dirs   : (K, 2) cluster-center direction cosines (solution planes)
    f0        : reference frequency (Hz)
    """

    sky_sim: SkyArrays
    sky_cal: SkyArrays
    sky_table: np.ndarray
    rho: np.ndarray
    rho_spatial: np.ndarray
    lm_dirs: np.ndarray
    f0: float
    # optional diffuse shapelet component in the center cluster
    # (simulate.py:360-383 random diffuse sky); None unless diffuse=True
    shapelet: object = None


def simulate_models(key, K=4, f0=150e6, Kc=80, M_weak=350, M_gauss=120,
                    M2=40, diffuse=False) -> CalibModels:
    """Random calibration sky: Kc-source center cluster, K-1 compact outlier
    clusters of M2 sources, M_weak point + M_gauss Gaussian background
    sources.  Reference: calibration/simulate.py:61-379.

    ``diffuse=True`` adds a random shapelet component at the phase center
    (the reference's random diffuse-sky option, simulate.py:360-383): the
    exact modes enter the simulated data, the perturbed twin the
    calibration model (cal/shapelets.py).
    """
    rng = _rng_of(key, salt=1)
    sim, cal = SkyDraw(), SkyDraw()
    table, lm_dirs = [], []

    # center cluster (id 0 here; reference writes id 1)
    lmin = 0.9
    l = (rng.random(Kc) - 0.5) * lmin
    m = (rng.random(Kc) - 0.5) * lmin
    sI = ((rng.random(Kc) * 90) + 10) / 10
    sI = sI / sI.min() * 0.03
    sP = rng.standard_normal(Kc)
    sim.add(l, m, sI, sP, 0)
    cal.add(l, m, sI, sP, 0)
    table.append([1, l.mean(), m.mean(), sI.mean(), sP.mean()])
    lm_dirs.append([l.mean(), m.mean()])
    rho = [sI.sum() * 100.0]

    # outlier clusters (reference simulate.py:232-312): compact (1e-3 rad)
    # M2-source clumps at bright off-center positions; calibration sky
    # divides fluxes by 100 (beam attenuation stand-in)
    lo = (rng.random(K - 1) - 0.5) * 0.7
    mo = (rng.random(K - 1) - 0.5) * 0.7
    sIo = ((rng.random(K - 1) * 900) + 100) / 10
    sIo = sIo / sIo.min() * 250.0
    sPo = rng.standard_normal(K - 1)
    for cj in range(K - 1):
        l2 = lo[cj] + (rng.random(M2) - 0.5) * 1e-3
        m2 = mo[cj] + (rng.random(M2) - 0.5) * 1e-3
        sI2 = rng.random(M2)
        sI2 = sI2 / sI2.sum() * sIo[cj]
        sim.add(l2, m2, sI2, sPo[cj], cj + 1)
        cal.add(l2, m2, sI2 / 100.0, sPo[cj], cj + 1)
        # NOTE reference quirk: skylmn.txt averages the *relative* offsets
        # (simulate.py:289-296), placing outliers at ~(0,0); we store the
        # true cluster center (the quantity the table is meant to carry).
        table.append([cj + 2, lo[cj], mo[cj], (sI2 / 100).mean(), sPo[cj]])
        lm_dirs.append([lo[cj], mo[cj]])
        rho.append(sI2.sum() / 1000.0 * 100.0)

    # weak background point sources, FOV ~16 deg (sim sky only, cluster K)
    sII = _powerlaw_flux(rng, M_weak, 0.01, 0.5)
    l0 = (rng.random(M_weak) - 0.5) * 15.5 * math.pi / 180
    m0 = (rng.random(M_weak) - 0.5) * 15.5 * math.pi / 180
    sim.add(l0, m0, sII, 0.0, K)

    # extended (Gaussian) background sources
    sI1 = _powerlaw_flux(rng, M_gauss, 0.01, 0.5)
    l1 = (rng.random(M_gauss) - 0.5) * 15.5 * math.pi / 180
    m1 = (rng.random(M_gauss) - 0.5) * 15.5 * math.pi / 180
    for i in range(M_gauss):
        g = np.asarray([(rng.random() - 0.5) * 0.5 * math.pi / 180,
                        (rng.random() - 0.5) * 0.5 * math.pi / 180,
                        (rng.random() - 0.5) * math.pi])
        sim.add(l1[i], m1[i], sI1[i], 0.0, K, gauss=g)

    shp = None
    if diffuse:
        from smartcal_tpu.cal.shapelets import random_shapelet

        shp = random_shapelet(rng)

    return CalibModels(
        sky_sim=sim.build(K + 1, f0), sky_cal=cal.build(K, f0),
        sky_table=np.asarray(table, np.float32),
        rho=np.asarray(rho, np.float32),
        rho_spatial=np.full(K, 0.1, np.float32),
        lm_dirs=np.asarray(lm_dirs, np.float32), f0=float(f0),
        shapelet=shp)


# ---------------------------------------------------------------------------
# Demixing sky (target field + A-team outliers)
# ---------------------------------------------------------------------------

class DemixModels(NamedTuple):
    """Output of :func:`simulate_demixing_sky` — the array form of what the
    reference assembles from base.sky/base.cluster + the random target field
    (generate_data.py:1004-1140).  Cluster order: 0..K-2 = A-team outliers,
    K-1 = target (matching the reference where target is the LAST direction
    among the calibrated ones and weak sources live in an extra cluster).

    separations/azimuth/elevation: per calibrated cluster in DEGREES (the
    casacore-measures units the reference feeds its metadata/hints,
    influence_tools.py:16-159), re-done in pure math
    fluxes: apparent flux sum per calibrated cluster
    """

    sky_sim: SkyArrays
    sky_cal: SkyArrays
    rho: np.ndarray
    separations: np.ndarray
    azimuth: np.ndarray
    elevation: np.ndarray
    fluxes: np.ndarray
    lm_dirs: np.ndarray
    f0: float


def ateam_components(key, ra0, dec0, f0, n_comp=30):
    """Synthetic A-team clusters: for each of the 5 sources, ``n_comp``
    components scattered within ~0.3 deg of the true position, total flux at
    the catalog scale.  Stand-in for the reference's checked-in
    ``base.sky``/``base.cluster`` models (demixing/base.sky, 535 components)
    — same role (bright off-axis interferers), independently generated."""
    from smartcal_tpu.cal import coords

    rng = _rng_of(key, salt=2)
    comp = SkyDraw()
    for i, (ra, dec) in enumerate(obs_mod.ATEAM_DIRS):
        l, m, _ = coords.radectolm(ra, dec, ra0, dec0)
        l, m = float(l), float(m)
        dl = (rng.random(n_comp) - 0.5) * 0.01
        dm = (rng.random(n_comp) - 0.5) * 0.01
        w = rng.random(n_comp)
        flux = w / w.sum() * obs_mod.ATEAM_FLUX[i]
        sp = np.full(n_comp, -0.7) + 0.1 * rng.standard_normal(n_comp)
        comp.add(l + dl, m + dm, flux, sp, i)
    return comp


def simulate_demixing_sky(key, ra0, dec0, t0, f0, K=6, Kc=40, M_weak=350,
                          M_gauss=120, beam_atten=True) -> DemixModels:
    """Target field + A-team sky for the demixing workloads.

    Reference: generate_data.py:1004-1140 — Kc target sources (power-law
    fluxes in [0.1, 200]), weak + Gaussian background in a 25.5-deg FOV,
    A-team clusters prepended from base files.  ``beam_atten`` applies a
    smooth elevation-dependent attenuation to the A-team apparent fluxes
    (sim and cal skies alike, and the analytic rho) — the role of the
    reference's ``-E 1`` beam during simulation; False uses catalog fluxes.
    """
    from smartcal_tpu.cal import coords

    rng = _rng_of(key, salt=3)
    n_ateam = K - 1
    lst0 = obs_mod.OMEGA_EARTH * t0 % TWO_PI

    # A-team outlier clusters 0..K-2
    at = ateam_components(key, ra0, dec0, f0)
    sim, cal = SkyDraw(), SkyDraw()
    sep, azl, ell, fluxes, lm_dirs = [], [], [], [], []
    atten = []
    for i in range(n_ateam):
        ra, dec = obs_mod.ATEAM_DIRS[i]
        s = float(coords.angular_separation(ra0, dec0, ra, dec))
        az, el = coords.azel_from_radec(ra, dec, lst0, obs_mod.LOFAR_LAT)
        sep.append(math.degrees(s))
        azl.append(math.degrees(float(az)))
        ell.append(math.degrees(float(el)))
        # elevation-driven apparent-flux attenuation (beam stand-in):
        # sources below the horizon are strongly suppressed
        if beam_atten:
            a = 0.05 + 0.95 * max(0.0, math.sin(max(float(el), 0.0))) ** 2
        else:
            a = 1.0
        atten.append(a)
        l_i, m_i = at.l[i], at.m[i]
        f_i = at.flux[i] * a
        sim.add(l_i, m_i, f_i, at.sp[i], i)
        cal.add(l_i, m_i, f_i, at.sp[i], i)
        fluxes.append(float(np.sum(f_i)))
        lm_dirs.append([float(np.mean(l_i)), float(np.mean(m_i))])

    # target cluster K-1 at the phase center
    l = (rng.random(Kc) - 0.5) * 0.2
    m = (rng.random(Kc) - 0.5) * 0.2
    sI = _powerlaw_flux(rng, Kc, 0.1, 200.0)
    sP = rng.standard_normal(Kc)
    sim.add(l, m, sI, sP, K - 1)
    cal.add(l, m, sI, sP, K - 1)
    az0, el0 = coords.azel_from_radec(ra0, dec0, lst0, obs_mod.LOFAR_LAT)
    sep.append(0.0)
    azl.append(math.degrees(float(az0)))
    ell.append(math.degrees(float(el0)))
    fluxes.append(float(sI.sum()))
    lm_dirs.append([float(l.mean()), float(m.mean())])

    # weak + Gaussian background (sim only, cluster K), 25.5-deg FOV
    sII = _powerlaw_flux(rng, M_weak, 0.01, 0.5)
    l0 = (rng.random(M_weak) - 0.5) * 25.5 * math.pi / 180
    m0 = (rng.random(M_weak) - 0.5) * 25.5 * math.pi / 180
    sim.add(l0, m0, sII, 0.0, K)
    sI1 = _powerlaw_flux(rng, M_gauss, 0.01, 0.5)
    l1 = (rng.random(M_gauss) - 0.5) * 25.5 * math.pi / 180
    m1 = (rng.random(M_gauss) - 0.5) * 25.5 * math.pi / 180
    for i in range(M_gauss):
        g = np.asarray([(rng.random() - 0.5) * 0.5 * math.pi / 180,
                        (rng.random() - 0.5) * 0.5 * math.pi / 180,
                        (rng.random() - 0.5) * math.pi])
        sim.add(l1[i], m1[i], sI1[i], 0.0, K, gauss=g)

    # analytic rho: A-team at catalog scale x attenuation, target
    # sum(sI)*10/Kc (generate_data.py:1077)
    rho = np.asarray(
        [obs_mod.ATEAM_FLUX[i] * atten[i] * 0.1 for i in range(n_ateam)]
        + [sI.sum() * 10.0 / Kc], np.float32)

    return DemixModels(
        sky_sim=sim.build(K + 1, f0), sky_cal=cal.build(K, f0),
        rho=rho, separations=np.asarray(sep, np.float32),
        azimuth=np.asarray(azl, np.float32),
        elevation=np.asarray(ell, np.float32),
        fluxes=np.asarray(fluxes, np.float32),
        lm_dirs=np.asarray(lm_dirs, np.float32), f0=float(f0))


def write_dp3_parsets(outdir, sourcedb="sky_bbs.txt", tdelta=10):
    """Emit DP3 parsets for external cross-checks of the same data
    (reference simulate.py:142-188: demix / ddecal / predict-subtract
    steps, L-BFGS solver settings matching the in-framework solver's
    robust-L-BFGS configuration).  Pure text emission — DP3 itself is an
    external tool; nothing in-framework consumes these."""
    import os

    def w(name, step, opts):
        with open(os.path.join(outdir, name), "w") as fh:
            fh.write(f"steps=[{step}]\n")
            for k, v in opts.items():
                fh.write(f"{step}.{k}={v}\n")

    w("test_demix.parset", "demix", {
        "type": "demixer", "blrange": "[60,100000]",
        "demixtimestep": tdelta, "demixfreqstep": 16, "ntimechunk": 4,
        "uselbfgssolver": "true", "lbfgs.historysize": 10, "maxiter": 30,
        "lbfgs.robustdof": 200})
    w("test_ddecal.parset", "ddecal", {
        "type": "ddecal", "h5parm": "./solutions.h5",
        "sourcedb": sourcedb, "mode": "fulljones", "uvlambdamin": 30,
        "usebeammodel": "true", "beamproximitylimit": 0.1,
        "solveralgorithm": "lbfgs", "solverlbfgs.dof": 200.0,
        "solverlbfgs.iter": 4, "solverlbfgs.minibatches": 3,
        "solverlbfgs.history": 10, "maxiter": 50,
        "smoothnessconstraint": 1e6, "nchan": 16, "stepsize": 1e-3,
        "solint": tdelta})
    w("test_predict.parset", "predict", {
        "type": "h5parmpredict", "sourcedb": sourcedb,
        "usebeammodel": "true", "applycal.correction": "fulljones",
        "applycal.parmdb": "./solutions.h5", "operation": "subtract"})
    return [os.path.join(outdir, n) for n in
            ("test_demix.parset", "test_ddecal.parset",
             "test_predict.parset")]


# ---------------------------------------------------------------------------
# Systematic-error Jones solutions
# ---------------------------------------------------------------------------

def synth_solutions(key, K, n_stations, Ts, freqs, f0, amp=1.0,
                    spatial_term=False, spalpha=0.95, lm_dirs=None):
    """Synthetic per-direction systematic errors J: (Nf, Ts, K, 2N, 2, 2)
    split-real float32.

    Per direction: 8N base values (optionally the mix of a random part and
    spatially smooth planes a0*l + a1*m + a2 over cluster centers), +1 on the
    diagonal real parts, modulated by a random quadratic polynomial over
    normalized frequency and a random cosine over time.
    Reference: simulate.py:386-435 (amp=1, spatial planes),
    generate_data.py:1154-1190 (amp=0.01, no spatial term).
    """
    rng = _rng_of(key, salt=4)
    N8 = 8 * n_stations
    freqs = np.asarray(freqs, np.float64)
    ff = (freqs - f0) / f0                                  # (Nf,)
    Nf = ff.shape[0]

    if spatial_term:
        a0, a1, a2 = rng.standard_normal((3, N8))
        a0, a1, a2 = (v / np.linalg.norm(v) for v in (a0, a1, a2))
        lm = np.asarray(lm_dirs)                            # (K, 2)
        base = np.empty((K, N8))
        for ck in range(K):
            rp = rng.standard_normal(N8)
            b = ((1 - spalpha) * rp / np.linalg.norm(rp)
                 + spalpha * (a0 * lm[ck, 0] + a1 * lm[ck, 1] + a2))
            base[ck] = b / np.linalg.norm(b)
    else:
        base = rng.standard_normal((K, N8)) * amp
    base[:, 0::8] += 1.0
    base[:, 6::8] += 1.0

    # random quadratic frequency polynomial per (k, value)
    beta = rng.standard_normal((K, N8, 3))
    freqpol = (beta[..., 0:1] + beta[..., 1:2] * ff[None, None, :]
               + beta[..., 2:3] * ff[None, None, :] ** 2)   # (K, N8, Nf)
    gs = base[:, :, None] * freqpol

    # random cosine time modulation per (k, value), shared across freq
    tr = np.arange(Ts) / Ts
    tb = rng.standard_normal((K, N8, 4))
    tb = tb / np.linalg.norm(tb, axis=-1, keepdims=True)
    timepol = (1.0 + tb[..., 0:1]
               + tb[..., 1:2] * np.cos(tr[None, None, :] * tb[..., 2:3]
                                       + tb[..., 3:4]))     # (K, N8, Ts)

    full = gs[:, :, None, :] * timepol[..., None]           # (K, N8, Ts, Nf)
    # 8 values per station: [J00re, J00im, J01re, J01im, J10re, J10im,
    # J11re, J11im] -> (N, 2, 2, re/im)
    full = full.reshape(K, n_stations, 2, 2, 2, Ts, Nf)
    J = np.transpose(full, (6, 5, 0, 1, 2, 3, 4))           # (Nf,Ts,K,N,2,2,2)
    J = J.reshape(Nf, Ts, K, 2 * n_stations, 2, 2)
    return J.astype(np.float32)


def identity_solutions(K, n_stations, Ts, Nf):
    """J = I for every direction/station (the unperturbed-sky case)."""
    J = np.zeros((Nf, Ts, K, 2 * n_stations, 2, 2), np.float32)
    eye = np.eye(2, dtype=np.float32)
    for p in range(n_stations):
        J[:, :, :, 2 * p:2 * p + 2, :, 0] = eye
    return J


def add_noise(key, V, snr):
    """AWGN scaled so ||noise|| = snr * ||signal|| (reference addnoise.py:7-17;
    snr there is the noise-to-signal norm ratio).  V is split-real (..., 2)."""
    rng = _rng_of(key, salt=5)
    noise = rng.standard_normal(V.shape).astype(np.float32)
    noise -= noise.mean()
    scale = snr * np.linalg.norm(V) / max(np.linalg.norm(noise), 1e-30)
    return V + noise * scale, float(scale)


@jax.jit
def _apply_noise(V, noise, snr):
    nv = jnp.sqrt(jnp.sum(V * V))
    nn = jnp.sqrt(jnp.sum(noise * noise))
    scale = snr * nv / jnp.maximum(nn, 1e-30)
    return V + noise * scale, scale


def add_noise_device(key, V, snr):
    """:func:`add_noise` with the norm/scale/add on DEVICE.

    The noise draw keeps the host Generator (byte-identical stream to
    ``add_noise`` for the same key), but the signal array never
    round-trips to host: the legacy path's ``np.asarray(V)`` forced a
    device sync in the middle of episode construction.  Returns
    ``(V + scaled noise, scale)`` as device values; matches ``add_noise``
    to float32 reduction-order round-off (~1e-7 relative on the scale).
    """
    rng = _rng_of(key, salt=5)
    noise = rng.standard_normal(np.shape(V)).astype(np.float32)
    noise -= noise.mean()
    return _apply_noise(jnp.asarray(V), jnp.asarray(noise),
                        jnp.float32(snr))
