"""Calibration math layer: coordinates, coherency prediction, consensus
polynomials, residual Hessians / solution derivatives / influence kernels,
and the log-likelihood-ratio detector.

This is the TPU-native re-expression of the reference's
``calibration/calibration_tools.py`` (numpy/torch twin loops) as batched
einsum/segment-sum kernels that XLA can tile onto the MXU.
"""

from smartcal_tpu.cal import (coords, consensus, coherency, dataset,  # noqa: F401
                              fits_io, kernels, ms_io, skyio)
