"""Minimal first-party FITS image I/O (no astropy in the image).

The reference moves every image through FITS files: the envs read
``orig/{influenceI,data,res}.fits`` back from excon
(``calibration/calibenv.py:148-158``), and ``calmean.sh:1-100`` generates
a python script that inverse-variance-averages a list of FITS images into
``bar.fits`` carrying weighted BMAJ/BMIN, circular-mean BPA and weighted
CRVAL3/RESTFREQ headers.  The TPU framework keeps images as device arrays
end-to-end (``cal/imager.py``), but the FITS data edge is still the
interchange format a reference user expects for inspection and for
feeding external tools — this module provides it with plain numpy.

Scope: single-HDU image files, BITPIX -32/-64/16/32, the standard
2880-byte record structure, and the radio-image convention the reference
consumes — 4 axes (RA---SIN, DEC--SIN, FREQ, STOKES) with the pixel data
in the first two.  Not a general FITS library (no extensions, no tables,
no scaling beyond BSCALE/BZERO).
"""

from __future__ import annotations

import math
import os
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

BLOCK = 2880
_BITPIX_DTYPE = {-32: ">f4", -64: ">f8", 16: ">i2", 32: ">i4", 8: ">u1"}


# ---------------------------------------------------------------------------
# Header cards
# ---------------------------------------------------------------------------

def _card(key: str, value, comment: str = "") -> bytes:
    """One 80-byte header card (fixed format)."""
    key = key.upper()
    if len(key) > 8:
        # never truncate silently — an 8-char prefix can collide with a
        # standard card (RESTFREQX -> RESTFREQ) and vanish without error
        raise ValueError(f"FITS keyword {key!r} exceeds 8 characters")
    if value is None:                          # comment-style card
        text = f"{key:<8}{comment:<72}"[:80]
        return text.encode("ascii")
    if isinstance(value, bool):
        v = "T" if value else "F"
        body = f"= {v:>20}"
    elif isinstance(value, (int, np.integer)):
        body = f"= {int(value):>20}"
    elif isinstance(value, (float, np.floating)):
        body = f"= {float(value):>20.13E}"
    else:                                      # string
        # never truncate silently (same policy as over-length keywords),
        # and never truncate AFTER escaping — cutting a doubled '' pair
        # in half would leave an unbalanced quote (ADVICE r4 item 1).
        # No CONTINUE-card support, so an unrepresentable value raises.
        s = str(value).replace("'", "''")
        if len(s) > 67:
            raise ValueError(
                f"FITS string value for {key} exceeds 67 characters "
                f"after quote escaping: {str(value)!r}")
        body = f"= '{s:<8}'"
    text = f"{key:<8}{body}"
    if comment:
        text += f" / {comment}"
    return f"{text:<80}"[:80].encode("ascii")


def _parse_value(raw: str):
    raw = raw.strip()
    if raw.startswith("'"):
        end = raw.rfind("'")
        return raw[1:end].replace("''", "'").rstrip()
    if raw in ("T", "F"):
        return raw == "T"
    try:
        if any(c in raw for c in ".EeDd") and not raw.lstrip("+-").isdigit():
            return float(raw.replace("D", "E").replace("d", "e"))
        return int(raw)
    except ValueError:
        return raw


def _pad(buf: bytes, fill: bytes = b" ") -> bytes:
    rem = (-len(buf)) % BLOCK
    return buf + fill * rem


# ---------------------------------------------------------------------------
# Write
# ---------------------------------------------------------------------------

def write_image(path, data, *, ra0: float = 0.0, dec0: float = 0.0,
                cell_rad: float = 1e-5, freq: float = 150e6,
                dfreq: float = 1e6, bmaj: Optional[float] = None,
                bmin: Optional[float] = None, bpa: Optional[float] = None,
                bunit: str = "JY/BEAM", object_name: str = "",
                extra: Optional[Dict[str, object]] = None) -> str:
    """Write a 2-D image as a 4-axis radio FITS file (BITPIX -32).

    ``data`` is (ny, nx) with the framework's row-major (l, m) layout
    (`cal/imager.pixel_grid`); stored as the standard (1, 1, ny, nx) cube
    so readers index ``[0, 0, y, x]`` exactly like the reference does
    (``calmean.sh``: ``itmp[0,0,XLOW:XHIGH,...]``).  ra0/dec0 in rad,
    cell_rad the pixel scale, freq on the FREQ axis (CRVAL3 — where
    ``calmean.sh`` reads it), bmaj/bmin/bpa in deg like excon emits.
    """
    img = np.ascontiguousarray(np.asarray(data, np.float32))
    if img.ndim != 2:
        raise ValueError(f"expected 2-D image, got shape {img.shape}")
    ny, nx = img.shape
    cdelt = math.degrees(cell_rad)
    std: List[Tuple[str, object, str]] = [
        ("SIMPLE", True, "first-party smartcal_tpu writer"),
        ("BITPIX", -32, ""),
        ("NAXIS", 4, ""),
        ("NAXIS1", nx, ""),
        ("NAXIS2", ny, ""),
        ("NAXIS3", 1, ""),
        ("NAXIS4", 1, ""),
        ("CTYPE1", "RA---SIN", ""),
        ("CRVAL1", math.degrees(ra0), ""),
        ("CDELT1", -cdelt, ""),
        ("CRPIX1", nx // 2 + 1.0, ""),
        ("CUNIT1", "deg", ""),
        ("CTYPE2", "DEC--SIN", ""),
        ("CRVAL2", math.degrees(dec0), ""),
        ("CDELT2", cdelt, ""),
        ("CRPIX2", ny // 2 + 1.0, ""),
        ("CUNIT2", "deg", ""),
        ("CTYPE3", "FREQ", ""),
        ("CRVAL3", float(freq), ""),
        ("CDELT3", float(dfreq), ""),
        ("CRPIX3", 1.0, ""),
        ("CUNIT3", "Hz", ""),
        ("CTYPE4", "STOKES", ""),
        ("CRVAL4", 1.0, ""),
        ("CDELT4", 1.0, ""),
        ("CRPIX4", 1.0, ""),
        ("BUNIT", bunit, ""),
    ]
    if object_name:
        std.append(("OBJECT", object_name, ""))
    for key, val in ((("BMAJ", bmaj), ("BMIN", bmin), ("BPA", bpa))):
        if val is not None:
            std.append((key, float(val), ""))
    # ``extra`` entries matching a standard card OVERRIDE it in place
    # (single card, original position) instead of appending a duplicate —
    # fits_mean uses this to carry an accepted base header's CRPIX /
    # CDELT1 / etc through to the output (ADVICE r4 item 2).  Structural
    # cards stay derived from the actual payload no matter what.
    structural = {"SIMPLE", "BITPIX"}

    def _is_structural(k: str) -> bool:
        # every NAXISn (any n, plus bare NAXIS) is payload-derived: a
        # carried-through NAXIS5 card from a 5-axis input would declare
        # an axis this 4-axis writer does not emit
        return k in structural or re.fullmatch(r"NAXIS\d*", k) is not None

    extra_d = {str(k).upper(): v for k, v in (extra or {}).items()
               if not _is_structural(str(k).upper())}
    cards: List[bytes] = []
    for key, val, com in std:
        if key in extra_d:
            val = extra_d.pop(key)
        cards.append(_card(key, val, com))
    for key, val in extra_d.items():
        cards.append(_card(key, val))
    cards.append(f"{'END':<80}".encode("ascii"))
    header = _pad(b"".join(cards))
    payload = _pad(img[None, None].astype(">f4").tobytes(), b"\0")
    tmp = str(path) + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(header)
        fh.write(payload)
    os.replace(tmp, str(path))
    return str(path)


# ---------------------------------------------------------------------------
# Read
# ---------------------------------------------------------------------------

def read_image(path) -> Tuple[np.ndarray, Dict[str, object]]:
    """(data, header): data squeezed to 2-D (ny, nx) float; header a dict
    of parsed cards.  Accepts any NAXIS as long as at most two axes are
    non-degenerate (the radio-image cube convention)."""
    with open(path, "rb") as fh:
        header: Dict[str, object] = {}
        while True:
            block = fh.read(BLOCK)
            if len(block) < BLOCK:
                raise ValueError(f"truncated FITS header in {path}")
            done = False
            for i in range(0, BLOCK, 80):
                card = block[i:i + 80].decode("ascii", "replace")
                key = card[:8].strip()
                if key == "END":
                    done = True
                    break
                if not key or key in ("COMMENT", "HISTORY"):
                    continue
                if card[8:10] != "= ":
                    continue
                body = card[10:]
                slash = _comment_split(body)
                header[key] = _parse_value(body[:slash])
            if done:
                break
        bitpix = int(header["BITPIX"])
        naxis = int(header["NAXIS"])
        shape = [int(header[f"NAXIS{i}"]) for i in range(naxis, 0, -1)]
        count = int(np.prod(shape)) if shape else 0
        dtype = np.dtype(_BITPIX_DTYPE[bitpix])
        nbytes = count * dtype.itemsize
        raw = fh.read(nbytes + (-nbytes) % BLOCK)[:nbytes]
        data = np.frombuffer(raw, dtype=dtype).reshape(shape)
    scale = float(header.get("BSCALE", 1.0))
    zero = float(header.get("BZERO", 0.0))
    arr = data.astype(np.float64) * scale + zero
    arr = np.squeeze(arr)
    if arr.ndim > 2:
        raise ValueError(f"more than two non-degenerate axes: {arr.shape}")
    return arr.astype(np.float32 if bitpix == -32 else np.float64), header


def _comment_split(body: str) -> int:
    """Index of the comment slash in a card body, quote-aware."""
    in_str = False
    for i, ch in enumerate(body):
        if ch == "'":
            in_str = not in_str
        elif ch == "/" and not in_str:
            return i
    return len(body)


# ---------------------------------------------------------------------------
# calmean: weighted average of FITS images
# ---------------------------------------------------------------------------

def fits_mean(paths: List[str], out: str, vmax: float = 0.01,
              vmin: float = 1e-12, box: Tuple[int, int, int, int] =
              (1, 10, 1, 10)) -> str:
    """Weighted mean of FITS images -> ``out`` (the calmean.sh role).

    Parity with the generated ``calmean_.py`` (``calmean.sh:1-100``):
    each accepted image contributes with inverse-variance weight
    sigma = 1/wt^2 where wt is the pixel std in ``box`` — images with
    wt outside (vmin, vmax) or NaN are rejected; BMAJ/BMIN and the FREQ
    value (CRVAL3, mirrored to RESTFREQ) are weight-averaged and BPA is
    a weighted circular mean; the output carries the first image's
    remaining header.  NOTE the shipped script currently short-circuits
    wt to a constant 0.99999 (every image accepted, plain mean) — with
    the default vmax=0.01 this implementation applies the variance gate
    the script documents; pass vmax=1.0 to reproduce the accept-all
    behavior.
    """
    if not paths:
        raise ValueError("fits_mean needs at least one input")
    xlo, xhi, ylo, yhi = box
    loaded = [read_image(p) for p in paths]
    acc = None
    wgt = 0.0
    bmaj = bmin = bpax = bpay = 0.0
    beam_wgt = 0.0
    freq0 = 0.0
    freq_wgt = 0.0                 # CRVAL3-carrying weight only — a
    # sigma that contributed no frequency must not dilute the average
    base_header = None             # first ACCEPTED image's header: the
    # output WCS must describe an image that actually contributed
    accepted = 0
    for img, hdr in loaded:
        wt = float(np.std(img[xlo:xhi, ylo:yhi]))
        if math.isnan(wt) or not (vmin < wt < vmax):
            continue
        if base_header is None:
            base_header = hdr
            acc = np.zeros_like(img, np.float64)
        sigma = 1.0 / (wt * wt)
        acc += img * sigma
        wgt += sigma
        accepted += 1
        if all(k in hdr for k in ("BMAJ", "BMIN", "BPA")):
            bmaj += float(hdr["BMAJ"]) * sigma
            bmin += float(hdr["BMIN"]) * sigma
            bpax += math.cos(math.radians(float(hdr["BPA"]))) * sigma
            bpay += math.sin(math.radians(float(hdr["BPA"]))) * sigma
            beam_wgt += sigma
        if "CRVAL3" in hdr:
            freq0 += float(hdr["CRVAL3"]) * sigma
            freq_wgt += sigma
    if base_header is None:        # every input rejected: zero image in
        base_header = loaded[0][1]  # the first input's frame
        acc = np.zeros_like(loaded[0][0], np.float64)
    if wgt == 0.0:
        wgt = 1.0                  # calmean.sh:78-80 parity
    mean = (acc / wgt).astype(np.float32)
    hdr = dict(base_header)
    freq = (freq0 / freq_wgt if freq_wgt > 0
            else float(hdr.get("CRVAL3", 0.0)))
    extra: Dict[str, object] = {"RESTFREQ": freq, "NIMAGES": accepted}
    beam = {}
    if beam_wgt > 0:
        beam = {"bmaj": bmaj / beam_wgt, "bmin": bmin / beam_wgt,
                "bpa": math.degrees(math.atan2(bpay / beam_wgt,
                                               bpax / beam_wgt))}
    # carry the base header's remaining cards through (the reference's
    # calmean copies the full first header): every card not computed
    # above rides along as an in-place override, so an externally
    # produced input with an off-center CRPIX or non-square CDELT1 keeps
    # a truthful WCS in the output (ADVICE r4 item 2).  Excluded: cards
    # re-derived from the payload (structural ones are dropped by
    # write_image itself), the weight-averaged quantities, and
    # BSCALE/BZERO — read_image already applied them to the pixels.
    computed = {"BSCALE", "BZERO", "EXTEND", "CRVAL3", "RESTFREQ",
                "NIMAGES"}
    if beam:
        computed |= {"BMAJ", "BMIN", "BPA"}
    for key, val in hdr.items():
        if key not in computed and key not in extra:
            extra[key] = val
    write_image(
        out, mean,
        ra0=math.radians(float(hdr.get("CRVAL1", 0.0))),
        dec0=math.radians(float(hdr.get("CRVAL2", 0.0))),
        cell_rad=math.radians(abs(float(hdr.get("CDELT2", 1e-5)))),
        freq=freq,
        bunit=str(hdr.get("BUNIT", "JY/BEAM")),
        extra=extra, **beam)
    return out
