"""Consensus-ADMM polynomial constraint matrices.

Parity targets: ``calibration/calibration_tools.py:524-585`` (Bpoly,
consensus_poly).  The reference materialises F (2N x 2N) and P (2N*Ne x 2N)
with explicit krons; both are kron products with the identity, so we compute
the small Ne-dimensional cores and expand only on request — the ADMM update
itself (see smartcal_tpu/cal/solver.py) uses the cores directly, which is the
shape XLA wants (small dense matmuls batched over direction/station axes).
"""

from functools import partial

import jax
import jax.numpy as jnp


def bernstein_basis(x, n):
    """Bernstein basis of order ``n`` evaluated at points ``x`` in [0,1].

    Returns (len(x), n+1): column r holds C(n,r) x^r (1-x)^(n-r).
    Reference: calibration_tools.py:524-547 (Bpoly).
    """
    x = jnp.asarray(x, jnp.float32)
    r = jnp.arange(n + 1, dtype=jnp.float32)
    # binomial via log-gamma: C(n,r) = n! / (r! (n-r)!)
    logc = (jax.lax.lgamma(jnp.asarray(n, jnp.float32) + 1.0)
            - jax.lax.lgamma(r + 1.0)
            - jax.lax.lgamma(jnp.asarray(n, jnp.float32) - r + 1.0))
    xx = x[:, None]
    # guard 0^0 = 1 at the endpoints
    px = jnp.where(r == 0, 1.0, xx ** r)
    p1x = jnp.where(r == n, 1.0, (1.0 - xx) ** (n - r))
    return jnp.exp(logc)[None, :] * px * p1x


def poly_basis(freqs, f0, n_terms, polytype=0, frange=None):
    """Frequency basis B (Nf x Ne): ordinary ((f-f0)/f0)^j or Bernstein.
    Reference: calibration_tools.py:559-568.

    ``frange``: (fmin, fmax) normalization interval for the Bernstein basis.
    REQUIRED when ``freqs`` is a local shard of a distributed frequency axis
    — the default (local min/max) would give each shard a different basis,
    corrupting any cross-shard consensus reduction."""
    freqs = jnp.asarray(freqs, jnp.float32)
    if polytype == 0:
        ff = (freqs - f0) / f0
        j = jnp.arange(n_terms, dtype=jnp.float32)
        return ff[:, None] ** j[None, :]
    fmin, fmax = frange if frange is not None else (freqs.min(), freqs.max())
    ff = (freqs - fmin) / (fmax - fmin)
    return bernstein_basis(ff, n_terms - 1)


@partial(jax.jit, static_argnames=("n_terms", "polytype"))
def consensus_cores(freqs, f0, n_terms, polytype=0, rho=0.0, alpha=0.0,
                    frange=None):
    """Small-core form of the consensus constraint.

    Returns (Bfull, Bi, fscale) where
      * Bfull: (Nf, Ne) frequency basis,
      * Bi: (Ne, Ne) = pinv(rho * sum_f b_f b_f^T + alpha I),
      * fscale: (Nf,) with fscale[f] = 1 - rho * b_f Bi b_f^T — the scalar
        that the reference's dense F = fscale * I_2N encodes
        (calibration_tools.py:578-583 notes F "is diagonal scalar").
    """
    bfull = poly_basis(freqs, f0, n_terms, polytype, frange=frange)
    bi_raw = rho * (bfull.T @ bfull) + alpha * jnp.eye(n_terms)
    bi = jnp.linalg.pinv(bi_raw)
    fscale = 1.0 - rho * jnp.einsum("fi,ij,fj->f", bfull, bi, bfull)
    return bfull, bi, fscale


def consensus_poly(n_terms, n_stations, freqs, f0, fidx, polytype=0,
                   rho=0.0, alpha=0.0):
    """Dense (F, P) with the reference's exact shapes, for golden tests and
    API parity: F (2N x 2N), P (2N*Ne x 2N).
    Reference: calibration_tools.py:551-585.

    F = (1 - rho b_f Bi b_f^T) I_2N;  P = kron(Bi b_f^T, I_2N).
    """
    bfull, bi, fscale = consensus_cores(
        jnp.asarray(freqs, jnp.float32), f0, n_terms, polytype, rho, alpha)
    eye2n = jnp.eye(2 * n_stations, dtype=jnp.float32)
    f_mat = fscale[fidx] * eye2n
    p_core = bi @ bfull[fidx][:, None]          # (Ne, 1)
    p_mat = jnp.kron(p_core, eye2n)             # (2N*Ne, 2N)
    return f_mat, p_mat
