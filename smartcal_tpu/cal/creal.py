"""Split-real complex arithmetic: complex tensors as float32 (..., 2) planes.

Why: TPUs have no native complex ALU — XLA lowers complex ops to real pairs,
and the axon TPU backend's complex lowering is unreliable (intermittent
UNIMPLEMENTED compile errors observed on hardware, 2026-07-29; see
cal/kernels.py).  Representing complex data as explicit real/imag planes is
also the genuinely TPU-native layout: a complex contraction becomes four real
einsums that tile straight onto the MXU, with no lowering surprises.

Convention: last axis length 2 = [real, imag].  All helpers are jit-safe.
``split``/``fuse`` are HOST-side (numpy) so device buffers never hold a
complex dtype.
"""

import jax.numpy as jnp
import numpy as np


def split(x):
    """numpy complex -> float32 (..., 2).  Host-side."""
    x = np.asarray(x)
    return np.stack([x.real, x.imag], axis=-1).astype(np.float32)


def fuse(x):
    """float32 (..., 2) -> numpy complex64.  Host-side."""
    x = np.asarray(x)
    return (x[..., 0] + 1j * x[..., 1]).astype(np.complex64)


def conj(a):
    return jnp.stack([a[..., 0], -a[..., 1]], axis=-1)


def mul(a, b):
    """Elementwise complex multiply (broadcasting)."""
    ar, ai = a[..., 0], a[..., 1]
    br, bi = b[..., 0], b[..., 1]
    return jnp.stack([ar * br - ai * bi, ar * bi + ai * br], axis=-1)


def mul_i(a):
    """Multiply by the imaginary unit: (re, im) -> (-im, re)."""
    return jnp.stack([-a[..., 1], a[..., 0]], axis=-1)


def abs2(a):
    """|z|^2, real output (drops the pair axis)."""
    return a[..., 0] ** 2 + a[..., 1] ** 2


def einsum(spec, a, b, compute_dtype=None):
    """Complex einsum over split operands: four real einsums.

    ``spec`` is a two-operand einsum spec over the NON-pair axes; the pair
    axis rides along implicitly.

    ``compute_dtype`` (cal/precision.py policy): when given, the OPERANDS
    are narrowed to it (e.g. bf16) while the contraction still
    accumulates in float32 (``preferred_element_type``) — the mixed-
    precision shape the MXU natively executes.  None = untouched f32
    (bit-identical to the pre-policy behavior).
    """
    ar, ai = a[..., 0], a[..., 1]
    br, bi = b[..., 0], b[..., 1]
    kw = {}
    if compute_dtype is not None:
        # the accumulation pin applies whenever a compute dtype is
        # requested — including operands that ALREADY arrive narrowed
        # (otherwise they would accumulate in their own dtype)
        kw["preferred_element_type"] = jnp.float32
        if compute_dtype != ar.dtype:
            ar, ai = ar.astype(compute_dtype), ai.astype(compute_dtype)
            br, bi = br.astype(compute_dtype), bi.astype(compute_dtype)
    rr = jnp.einsum(spec, ar, br, **kw)
    ii = jnp.einsum(spec, ai, bi, **kw)
    ri = jnp.einsum(spec, ar, bi, **kw)
    ir = jnp.einsum(spec, ai, br, **kw)
    return jnp.stack([rr - ii, ri + ir], axis=-1)


def matmul(a, b):
    """Complex matmul over the last two non-pair axes: a (..., M, K, 2) @
    b (..., K, N, 2) -> (..., M, N, 2)."""
    ar, ai = a[..., 0], a[..., 1]
    br, bi = b[..., 0], b[..., 1]
    rr = ar @ br - ai @ bi
    im = ar @ bi + ai @ br
    return jnp.stack([rr, im], axis=-1)


def solve(a, b):
    """Solve complex A x = b in split form via the real 2Nx2N block system
    [[Ar, -Ai], [Ai, Ar]] [xr; xi] = [br; bi].

    a: (..., N, N, 2), b: (..., N, M, 2) -> (..., N, M, 2).
    """
    ar, ai = a[..., 0], a[..., 1]
    br, bi = b[..., 0], b[..., 1]
    n = a.shape[-3]
    top = jnp.concatenate([ar, -ai], axis=-1)
    bot = jnp.concatenate([ai, ar], axis=-1)
    abig = jnp.concatenate([top, bot], axis=-2)          # (..., 2N, 2N)
    bbig = jnp.concatenate([br, bi], axis=-2)            # (..., 2N, M)
    x = jnp.linalg.solve(abig, bbig)
    return jnp.stack([x[..., :n, :], x[..., n:, :]], axis=-1)


def scale(a, s):
    """Multiply split-complex ``a`` by real scalar/array ``s``."""
    return a * jnp.asarray(s)[..., None]
