"""Direction-dependent calibration: per-direction Jones solve + consensus
ADMM across frequency.

This is the in-framework replacement for the reference's external
``sagecal-mpi_gpu`` binary (C++/CUDA/MPI), which every radio env shells out
to (``calibration/docal.sh:12``, ``demixing_rl/demixingenv.py:129``): a
distributed consensus-ADMM calibration over frequency sub-bands with a
polynomial smoothness constraint (Yatawatta-style: per sub-band solutions
J_f constrained to J_f = B_f Z with B the frequency polynomial basis, see
cal/consensus.py).

TPU-first design:
  * One frequency sub-band's Jones update is a smooth nonlinear least-squares
    problem solved with the in-framework L-BFGS (ops/lbfgs.py) — the whole
    ADMM loop is a ``lax.fori_loop`` and the (Nf, Ts) independent inner
    solves are ONE ``vmap``med ``lbfgs_solve`` call (the MPI rank-per-subband
    structure of sagecal-mpi becomes a batch axis).
  * Across-frequency consensus (the Z polynomial update) is a small reduction
    over the frequency axis: ``jnp.sum`` locally and ``lax.psum`` over the
    mesh axis named by ``axis_name`` when the frequency axis is sharded with
    ``shard_map`` — the MPI allreduce of the reference's backend becomes an
    ICI collective.
  * All math is split-real (cal/creal.py) so nothing depends on complex
    lowering; shapes follow cal/kernels.py conventions (samples time-major
    ck = t*B + b, baselines p < q row-major).

The solver's outputs (J solutions, Z global solutions, residual visibilities,
noise statistics) are exactly the quantities the reference reads back from
SAGECal's ``.solutions``/``zsol`` files and the MS CORRECTED_DATA column.
"""

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from smartcal_tpu.cal import consensus, creal
from smartcal_tpu.cal.kernels import baseline_indices, baseline_onehots
from smartcal_tpu.ops import lbfgs


class SolverConfig(NamedTuple):
    """Static configuration (shapes + iteration counts are compile-time).

    n_poly    : Ne consensus polynomial terms (sagecal -P)
    admm_iters: outer ADMM iterations (sagecal -A); reference envs vary this
                (demixingenv.py:113 maps an action to [5, 30])
    lbfgs_iters: L-BFGS iterations per ADMM outer iteration
    init_iters : chi2-only (no consensus prior) L-BFGS iterations run once
                before the ADMM loop when no warm start is given — large
                rho makes cold-started ADMM converge slowly, so the solve
                starts from the per-subband data optimum (the role of
                sagecal's initial non-consensus iterations)
    polytype  : 0 ordinary / 1 Bernstein (cal/consensus.poly_basis)
    """

    n_stations: int
    n_dirs: int
    n_poly: int = 3
    admm_iters: int = 10
    lbfgs_iters: int = 8
    init_iters: int = 40
    polytype: int = 0


class SolverStats(NamedTuple):
    """Telemetry threaded out of the jitted solve (``collect_stats=True``).

    Pure ADDITIONAL outputs computed from intermediates the solve already
    holds — the solution path is bit-identical with stats on or off
    (asserted by tests/test_obs.py).  In the fused solve the arrays are
    sized ``cfg.admm_iters`` (the static bound): entries past the
    executed count stay 0, and if a caller passes an ``admm_iters``
    override ABOVE the config (out of that argument's <= contract, but
    the fuzzy demixing env does it) the scatter drops the excess entries
    — ``admm_iters`` still reports the true count.  The host-segmented
    driver sizes them to the actual outer-iteration count.
    """

    admm_iters: jnp.ndarray    # () int32 outer iterations actually run
    primal_resid: jnp.ndarray  # (cfg.admm_iters,) consensus RMS ||J-BZ||
                               # per outer iteration (global over freq)
    inner_iters: jnp.ndarray   # (cfg.admm_iters,) int32 total L-BFGS
                               # iterations per outer iteration, all
                               # (Nf, Ts) lanes
    init_iters: jnp.ndarray    # () int32 total chi2-only init iterations
    n_segments: jnp.ndarray    # () int32 device dispatches (1 fused;
                               # the host-segmented driver counts its
                               # bounded dispatches)


class SolveResult(NamedTuple):
    J: jnp.ndarray          # (Nf, Ts, K, 2N, 2, 2) per-subband solutions
    Z: jnp.ndarray          # (Ts, K, Ne, 2N, 2, 2) global poly solutions
    residual: jnp.ndarray   # (Nf, T, B, 2, 2, 2) V - sum_k Jp C Jq^H
    sigma_res: jnp.ndarray  # () std of residual (all subbands)
    sigma_data: jnp.ndarray # () std of data
    final_cost: jnp.ndarray # (Nf, Ts) inner cost at the last ADMM
                            # iteration, in DATA units (rescaled from the
                            # internal normalization)
    stats: Optional[SolverStats] = None  # telemetry (collect_stats=True)


def _blocks(J, n_stations):
    """(..., 2N, 2, 2) -> (..., N, 2, 2, 2) station 2x2 blocks."""
    return J.reshape(J.shape[:-3] + (n_stations, 2, 2, 2))


def predict_vis_sr(J, C5, n_stations):
    """Model visibilities sum_k Jp C Jq^H: (Tc, B, 2, 2, 2).

    J : (K, 2N, 2, 2) split-real Jones; C5 : (K, Tc, B, 2, 2, 2).
    """
    p_idx, q_idx = baseline_indices(n_stations)
    J4 = _blocks(J, n_stations)
    Jp = J4[:, p_idx]
    Jq = J4[:, q_idx]
    JpC = creal.einsum("kbij,ktbjl->ktbil", Jp, C5)
    return creal.einsum("ktbil,kbml->tbim", JpC, creal.conj(Jq))


def coherency_to_chunks(C, B, Ts):
    """Kernel-convention C (K, T*B, 4, 2) -> solver chunks
    (Ts, K, Tdelta, B, 2, 2, 2) (order='F' 2x2 blocks, time-major rows)."""
    K = C.shape[0]
    C5 = jnp.swapaxes(C.reshape(K, -1, B, 2, 2, 2), -3, -2)  # (K, T, B, ...)
    T = C5.shape[1]
    td = T // Ts
    C6 = C5.reshape(K, Ts, td, B, 2, 2, 2)
    return jnp.moveaxis(C6, 0, 1)                            # (Ts, K, td, ...)


def vis_to_chunks(V, Ts):
    """(T, B, 2, 2, 2) -> (Ts, Tdelta, B, 2, 2, 2)."""
    T = V.shape[0]
    return V.reshape(Ts, T // Ts, *V.shape[1:])


def _chi2_planes(J, V5, C5, cfg: SolverConfig):
    """chi^2 = sum |V - sum_k Jp C Jq^H|^2 in a planes-major layout.

    The logical split-real layout (..., 2, 2, 2) puts the size-2 Jones/
    complex axes minor-most, which tiles terribly on the TPU VPU (the
    (8, 128) register tiles are ~97% padding) — measured 28 ms per
    batched cost+grad eval at LOFAR scale, dominating the whole ADMM
    solve.  Here the 2x2 complex algebra is unrolled in python over
    struct-of-arrays planes whose minor axis is baselines, so every
    elementwise op runs with full lanes; XLA fuses the unrolled chain.
    Same math, same operands, different loop order — the line-search
    objective only (predict_vis_sr stays the residual/simulation path).
    """
    Cp = jnp.transpose(C5, (0, 3, 4, 5, 1, 2))  # (K, j, l, c, Tc, B)
    Vp = jnp.transpose(V5, (2, 3, 4, 0, 1))     # (i, m, c, Tc, B)
    return _chi2_planes_core(J, Vp, Cp, cfg)


def _chi2_planes_core(J, Vp, Cp, cfg: SolverConfig):
    """`_chi2_planes` body on ALREADY-transposed operands.

    The data/coherency planes transposes are loop-invariant (only J
    changes across line-search/L-BFGS evaluations), but inside the cost
    function XLA re-runs them every eval — at LOFAR scale that is a
    ~58 MB coherency shuffle per evaluation.  Callers that evaluate
    repeatedly (`lbfgs_solve` via `_cost_fn_pretrans`) hoist them by
    preparing ``Vp = transpose(V5, (2,3,4,0,1))`` (i, m, c, Tc, B) and
    ``Cp = transpose(C5, (0,3,4,5,1,2))`` (K, j, l, c, Tc, B) once
    (measured: tools/bench_solve_eval.py)."""
    K = cfg.n_dirs
    p_idx, q_idx = baseline_indices(cfg.n_stations)
    J4 = J.reshape(K, cfg.n_stations, 2, 2, 2)
    Jp = jnp.moveaxis(J4[:, p_idx], 1, -1)      # (K, i, j, c, B)
    Jq = jnp.moveaxis(J4[:, q_idx], 1, -1)      # (K, m, l, c, B)

    # step 1: JpC[k, i, l] = sum_j Jp[k, i, j] C[k, j, l]   (complex)
    jpc = [[None] * 2 for _ in range(2)]
    for i in range(2):
        for l in range(2):
            tr = ti = 0.0
            for j in range(2):
                ar = Jp[:, i, j, 0][:, None, :]          # (K, 1, B)
                ai = Jp[:, i, j, 1][:, None, :]
                br = Cp[:, j, l, 0]                      # (K, Tc, B)
                bi = Cp[:, j, l, 1]
                tr = tr + ar * br - ai * bi
                ti = ti + ar * bi + ai * br
            jpc[i][l] = (tr, ti)

    # step 2: model[i, m] = sum_k sum_l JpC[k, i, l] conj(Jq[k, m, l]);
    # then chi2 accumulates (V - model)^2 over everything
    chi2 = 0.0
    for i in range(2):
        for m in range(2):
            mr = mi = 0.0
            for l in range(2):
                tr, ti = jpc[i][l]
                cr = Jq[:, m, l, 0][:, None, :]
                ci = Jq[:, m, l, 1][:, None, :]          # conj: -ci below
                mr = mr + tr * cr + ti * ci
                mi = mi - tr * ci + ti * cr
            dr = Vp[i, m, 0] - mr.sum(axis=0)            # sum over k
            di = Vp[i, m, 1] - mi.sum(axis=0)
            chi2 = chi2 + jnp.sum(dr * dr) + jnp.sum(di * di)
    return chi2


def _cost_fn(x, V5, C5, prior, half_rho, cfg: SolverConfig):
    """chi^2 + sum_k rho_k/2 ||J_k - prior_k||^2 (augmented Lagrangian with
    prior = B_f Z - Y/rho)."""
    K = cfg.n_dirs
    J = x.reshape(K, 2 * cfg.n_stations, 2, 2)
    chi2 = _chi2_planes(J, V5, C5, cfg)
    pr = jnp.sum((J - prior) ** 2, axis=(1, 2, 3))
    return chi2 + jnp.sum(half_rho * pr)


def _cost_fn_pretrans(x, Vp, Cp, prior, half_rho, cfg: SolverConfig):
    """`_cost_fn` on pre-transposed planes operands (see
    `_chi2_planes_core`): same math, but the loop-invariant data/model
    transposes are paid once by the caller instead of on every
    line-search evaluation."""
    K = cfg.n_dirs
    J = x.reshape(K, 2 * cfg.n_stations, 2, 2)
    chi2 = _chi2_planes_core(J, Vp, Cp, cfg)
    pr = jnp.sum((J - prior) ** 2, axis=(1, 2, 3))
    return chi2 + jnp.sum(half_rho * pr)


# One-hot (N, B) station-selection matrices: the scatter-free station<->
# baseline expansion.  Multiplying J planes by these reproduces the
# ``J4[:, p_idx]`` gather as a matmul — whose autodiff TRANSPOSE is
# another matmul (MXU) instead of the scatter-add a gather transposes to,
# the dominant non-elementwise op in the eval's backward pass.  The ONE
# implementation now lives in cal/kernels.baseline_onehots (shared with
# the formulation-optimized influence chain); this alias keeps the
# solver-local name its call sites and tests use.
_baseline_onehots = baseline_onehots


def _model_bilinear(Ja, Jb, Cp, onehot_p, onehot_q, cfg: SolverConfig):
    """K-summed model planes of ``F(Ja, Jb) = sum_k Ja_p C_k Jb_q^H``.

    Returns ``planes[i][m] = (re, im)``, each (Tc, B).  ``F`` is LINEAR
    in each Jones argument separately, which is what makes the
    line-search objective an exact quartic (`_quartic_phi_maker`): along
    ``x + alpha d`` the model is
    ``F(J,J) + alpha (F(D,J) + F(J,D)) + alpha^2 F(D,D)``.

    Station->baseline expansion is the one-hot matmul (scatter-free
    backward, `_baseline_onehots`); the 2x2 complex algebra is unrolled
    over struct-of-arrays planes whose minor axis is baselines so every
    elementwise op runs with full lanes."""
    K = cfg.n_dirs
    Ja5 = jnp.transpose(Ja.reshape(K, cfg.n_stations, 2, 2, 2),
                        (0, 2, 3, 4, 1))        # (K, i, j, c, N)
    Jb5 = jnp.transpose(Jb.reshape(K, cfg.n_stations, 2, 2, 2),
                        (0, 2, 3, 4, 1))
    Jp = jnp.einsum("kijcn,nb->kijcb", Ja5, onehot_p)
    Jq = jnp.einsum("kijcn,nb->kijcb", Jb5, onehot_q)

    jpc = [[None] * 2 for _ in range(2)]
    for i in range(2):
        for l in range(2):
            tr = ti = 0.0
            for j in range(2):
                ar = Jp[:, i, j, 0][:, None, :]          # (K, 1, B)
                ai = Jp[:, i, j, 1][:, None, :]
                br = Cp[:, j, l, 0]                      # (K, Tc, B)
                bi = Cp[:, j, l, 1]
                tr = tr + ar * br - ai * bi
                ti = ti + ar * bi + ai * br
            jpc[i][l] = (tr, ti)

    planes = [[None] * 2 for _ in range(2)]
    for i in range(2):
        for m in range(2):
            mr = mi = 0.0
            for l in range(2):
                tr, ti = jpc[i][l]
                cr = Jq[:, m, l, 0][:, None, :]
                ci = Jq[:, m, l, 1][:, None, :]          # conj: -ci below
                mr = mr + tr * cr + ti * ci
                mi = mi - tr * ci + ti * cr
            planes[i][m] = (mr.sum(axis=0), mi.sum(axis=0))  # sum over k
    return planes


def _chi2_planes_onehot(J, Vp, Cp, onehot_p, onehot_q, cfg: SolverConfig):
    """`_chi2_planes_core` with the station->baseline expansion done by
    one-hot matmuls instead of gathers (see `_baseline_onehots`).  Same
    math to float round-off; parity is asserted in tests and the
    formulation choice is measured, not assumed
    (tools/bench_solve_eval.py)."""
    planes = _model_bilinear(J, J, Cp, onehot_p, onehot_q, cfg)
    chi2 = 0.0
    for i in range(2):
        for m in range(2):
            mr, mi = planes[i][m]
            dr = Vp[i, m, 0] - mr
            di = Vp[i, m, 1] - mi
            chi2 = chi2 + jnp.sum(dr * dr) + jnp.sum(di * di)
    return chi2


def _quartic_phi_maker(Vp, Cp, onehots, prior, half_rho, cfg: SolverConfig):
    """Exact-polynomial line-search factory for the calibration cost.

    The model is bilinear in the Jones parameters, so along a search
    direction the residual is exactly
    ``R(alpha) = R0 - alpha P1 - alpha^2 P2`` with
    ``R0 = V - F(J,J)``, ``P1 = F(D,J) + F(J,D)``, ``P2 = F(D,D)`` —
    and ``phi(alpha) = |R(alpha)|^2 + prior`` is an exact degree-4
    polynomial.  Its five coefficients cost four bilinear model
    evaluations ONCE per line search; afterwards every strong-Wolfe /
    zoom probe (`ops.lbfgs.strong_wolfe_cubic` executes up to ~15 of
    them per search) is O(1) scalar arithmetic instead of a full-model
    jvp.  No approximation: values and directional derivatives are the
    polynomial's, exact to float round-off.

    Returned ``maker(fun, x, d)`` matches the `ops.lbfgs._phi_maker`
    contract (``fun`` is unused — the structure replaces it).
    """
    onehot_p, onehot_q = onehots

    def maker(fun, x, d):
        del fun
        K = cfg.n_dirs
        J = x.reshape(K, 2 * cfg.n_stations, 2, 2)
        D = d.reshape(J.shape)
        # cross term P1 = F(D,J) + F(J,D) from the two MIXED bilinear
        # evaluations directly (four model evals total).  The previous
        # three-eval polarization-identity form
        # P1 = F(J+D,J+D) - F(J,J) - F(D,D) cancels CATASTROPHICALLY in
        # f32 once |D| << |J| (late L-BFGS iterations: |p1| ~ |D|/|J| of
        # |ms|, so at |D| ~ 1e-4 |J| the subtraction keeps ~no bits),
        # feeding the Wolfe probes a wrong c1 slope exactly when the
        # search needs small-step accuracy.  One extra bilinear eval
        # buys an exact-to-round-off P1 at every step scale
        # (tests/test_calib_pipeline.py pins the small-step regime).
        m0 = _model_bilinear(J, J, Cp, onehot_p, onehot_q, cfg)
        m2 = _model_bilinear(D, D, Cp, onehot_p, onehot_q, cfg)
        mdj = _model_bilinear(D, J, Cp, onehot_p, onehot_q, cfg)
        mjd = _model_bilinear(J, D, Cp, onehot_p, onehot_q, cfg)
        c0 = c1 = c2 = c3 = c4 = jnp.asarray(0.0, x.dtype)
        for i in range(2):
            for m in range(2):
                for comp in range(2):
                    r0 = Vp[i, m, comp] - m0[i][m][comp]
                    p2 = m2[i][m][comp]
                    p1 = mdj[i][m][comp] + mjd[i][m][comp]
                    c0 = c0 + jnp.sum(r0 * r0)
                    c1 = c1 - 2.0 * jnp.sum(r0 * p1)
                    c2 = c2 + jnp.sum(p1 * p1) - 2.0 * jnp.sum(r0 * p2)
                    c3 = c3 + 2.0 * jnp.sum(p1 * p2)
                    c4 = c4 + jnp.sum(p2 * p2)
        e = J - prior
        c0 = c0 + jnp.sum(half_rho * jnp.sum(e * e, axis=(1, 2, 3)))
        c1 = c1 + 2.0 * jnp.sum(half_rho * jnp.sum(e * D, axis=(1, 2, 3)))
        c2 = c2 + jnp.sum(half_rho * jnp.sum(D * D, axis=(1, 2, 3)))

        def phi(alpha):
            a = jnp.asarray(alpha, x.dtype)
            val = c0 + a * (c1 + a * (c2 + a * (c3 + a * c4)))
            der = c1 + a * (2.0 * c2 + a * (3.0 * c3 + a * 4.0 * c4))
            return val, der

        return phi

    return maker


def _cost_fn_onehot(x, Vp, Cp, onehots, prior, half_rho,
                    cfg: SolverConfig):
    """`_cost_fn` on pre-transposed operands with matmul-based station
    expansion — the PRODUCTION inner-evaluation path (both ADMM
    drivers).  Measured on the single host core at N=62/Nf=8
    (tools/bench_solve_eval.py): 2.6x faster value_and_grad and 1.35x
    faster line-search jvp than the gather-based `_cost_fn`, with the
    value bit-identical and the gradient equal to 2e-7 relative.  The
    win is the backward pass: a gather transposes to a scatter-add,
    the one-hot matmul transposes to another matmul."""
    K = cfg.n_dirs
    J = x.reshape(K, 2 * cfg.n_stations, 2, 2)
    chi2 = _chi2_planes_onehot(J, Vp, Cp, onehots[0], onehots[1], cfg)
    pr = jnp.sum((J - prior) ** 2, axis=(1, 2, 3))
    return chi2 + jnp.sum(half_rho * pr)


def _eval_operands(V6, C7):
    """Pre-transposed planes operands for the inner evaluations: paid
    once per solve (loop-invariant — only J changes between
    evaluations), saving a full re-layout of the ~58 MB (LOFAR scale)
    coherency tensor on every line-search evaluation.

    V6 (Nf, Ts, td, B, 2, 2, 2)    -> Vp (Nf, Ts, i, m, c, td, B)
    C7 (Nf, Ts, K, td, B, 2, 2, 2) -> Cp (Nf, Ts, K, j, l, c, td, B)
    """
    Vp = jnp.transpose(V6, (0, 1, 4, 5, 6, 2, 3))
    Cp = jnp.transpose(C7, (0, 1, 2, 5, 6, 7, 3, 4))
    return Vp, Cp


# ---- pieces shared by the fused (solve_admm) and host-segmented
# (solve_admm_host) drivers: ONE copy of the numerically sensitive
# formulas — normalization, consensus conditioning, dual update, sigmas —
# parameterized by axis_name (None when the frequency axis is local).

def _prep(V, C, freqs, f0, rho, cfg, Ts, freq_range, axis_name):
    """Scale normalization + chunking + consensus operators.

    Scale invariance: radio fluxes span ~0.01..1e4 Jy, so chi2 in raw
    units overflows float32 line-search arithmetic.  Normalize data and
    model by the data scale and rho by its square — the minimizer (J, Z)
    is unchanged, the arithmetic stays O(1).  Undone on the outputs by
    _finalize."""
    B = V.shape[2]
    vmean = jnp.mean(V * V)
    if axis_name is not None:
        vmean = lax.pmean(vmean, axis_name)
    data_scale = jnp.sqrt(vmean) + 1e-20
    V = V / data_scale
    C = C / data_scale
    rho = jnp.asarray(rho) / (data_scale * data_scale)
    V6 = jax.vmap(lambda v: vis_to_chunks(v, Ts))(V)     # (Nf,Ts,td,B,...)
    C7 = jax.vmap(lambda c: coherency_to_chunks(c, B, Ts))(C)
    # frequency basis, shared across directions; per-frequency row b_f
    bfull = consensus.poly_basis(freqs, f0, cfg.n_poly, cfg.polytype,
                                 frange=freq_range)      # (Nf, Ne)
    # Bi_k = pinv(rho_k sum_f b_f b_f^T): needs the GLOBAL sum over freq
    btb = bfull.T @ bfull
    if axis_name is not None:
        btb = lax.psum(btb, axis_name)
    # conditioning eps must scale with rho*btb: after the data-scale
    # normalization rho can be tiny, and a fixed eps would bias Z to zero
    tr = jnp.trace(btb) / cfg.n_poly
    Bi = jax.vmap(
        lambda r: jnp.linalg.pinv(
            r * btb + (1e-6 * r * tr + 1e-30) * jnp.eye(cfg.n_poly)))(rho)
    return V6, C7, rho, data_scale, bfull, Bi


def _bz(bfull, Z):
    """B_f Z: (Nf, Ts, K, 2N, 2, 2) from Z (Ts, K, Ne, 2N, 2, 2)."""
    return jnp.einsum("fe,tkenij->ftknij", bfull, Z)


def _z_update(bfull, Bi, rho, J, Y, axis_name=None):
    # S_k = sum_f b_f (rho_k J_fk + Y_fk)  -> (Ts, K, Ne, 2N, 2, 2)
    w = rho[None, None, :, None, None, None] * J + Y
    S = jnp.einsum("fe,ftknij->tkenij", bfull, w)
    if axis_name is not None:
        S = lax.psum(S, axis_name)
    return jnp.einsum("kem,tkmnij->tkenij", Bi, S)


def _finalize(J, V6, C7, data_scale, cost, cfg, T, axis_name=None):
    """Residual over the full data + noise statistics, in DATA units."""
    B = V6.shape[3]
    N = cfg.n_stations

    def resid_f(Jf, Vf, Cf):
        r = jax.vmap(lambda j, v, c: v - predict_vis_sr(j, c, N))(Jf, Vf, Cf)
        return r.reshape(T, B, 2, 2, 2)

    residual = jax.vmap(resid_f)(J, V6, C7) * data_scale
    n_res = jnp.sum(residual * residual)
    n_dat = jnp.sum(V6 * V6) * data_scale * data_scale
    count = jnp.asarray(residual.size, residual.dtype)
    if axis_name is not None:
        n_res = lax.psum(n_res, axis_name)
        n_dat = lax.psum(n_dat, axis_name)
        count = lax.psum(count, axis_name)
    return (residual, jnp.sqrt(n_res / count), jnp.sqrt(n_dat / count),
            cost * data_scale * data_scale)


@partial(jax.jit,
         static_argnames=("cfg", "axis_name", "n_chunks", "collect_stats"))
def solve_admm(V, C, freqs, f0, rho, cfg: SolverConfig, J0=None,
               axis_name: Optional[str] = None,
               admm_iters: Optional[jnp.ndarray] = None,
               freq_range=None, n_chunks: Optional[int] = None,
               collect_stats: bool = False) -> SolveResult:
    """Consensus-ADMM calibration over (possibly sharded) frequency sub-bands.

    V     : (Nf, T, B, 2, 2, 2) observed visibilities (split-real 2x2)
    C     : (Nf, K, T*B, 4, 2) model coherencies (kernel convention)
    freqs : (Nf,) Hz; f0 scalar reference frequency
    rho   : (K,) per-direction ADMM regularization (the RL action in the
            calibration workload)
    J0    : optional warm start (Nf, Ts, K, 2N, 2, 2)
    axis_name : mesh axis of the sharded frequency dimension — when given,
            cross-frequency sums become ``lax.psum`` (ICI collective) and Nf
            here is the LOCAL shard size
    admm_iters : optional traced iteration count (<= cfg.admm_iters), the
            dynamic ``-A`` of the demixing action space — avoids a recompile
            per maxiter value
    freq_range : (fmin, fmax) global band edges; REQUIRED with
            ``axis_name`` + Bernstein polytype so every shard builds the
            same basis (see cal/consensus.poly_basis)

    n_chunks : number of solution intervals Ts (sagecal -t buckets); when
            None, Ts is derived from J0 (or 1).  Pass n_chunks WITHOUT a J0
            warm start to get per-interval solutions plus the chi2-only
            init phase (a J0 warm start skips init_iters).
    collect_stats : static; when True the result's ``stats`` field carries
            per-outer-iteration consensus residuals and L-BFGS iteration
            counts (SolverStats) — additional outputs only, the solution
            path is bit-identical either way.
    """
    if axis_name is not None and cfg.polytype == 1 and freq_range is None:
        raise ValueError(
            "sharded frequency axis with Bernstein polytype needs explicit "
            "freq_range=(fmin, fmax) — local shard min/max would build "
            "incompatible bases across shards")
    Nf, T, B = V.shape[0], V.shape[1], V.shape[2]
    K, N = cfg.n_dirs, cfg.n_stations
    if n_chunks is not None:
        Ts = n_chunks
        if J0 is not None:
            assert J0.shape[1] == Ts
    else:
        Ts = 1 if J0 is None else J0.shape[1]
    niter = cfg.admm_iters if admm_iters is None else admm_iters

    V6, C7, rho, data_scale, bfull, Bi = _prep(
        V, C, freqs, f0, rho, cfg, Ts, freq_range, axis_name)

    warm = J0 is not None
    if not warm:
        eye = jnp.zeros((2, 2, 2)).at[:, :, 0].set(jnp.eye(2))
        J0 = jnp.broadcast_to(eye, (Nf, Ts, K, N, 2, 2, 2)).reshape(
            Nf, Ts, K, 2 * N, 2, 2)

    half_rho = 0.5 * rho
    # loop-invariant eval operands: transposed planes + one-hot station
    # expansion matrices (see _cost_fn_onehot) — prepared ONCE, outside
    # the optimizer loops
    Vp, Cp = _eval_operands(V6, C7)
    onehots = _baseline_onehots(N, V6.dtype)

    def inner_solve(x0, vp, cp, prior):
        fun = lambda x: _cost_fn_onehot(x, vp, cp, onehots, prior,
                                        half_rho, cfg)
        pm = _quartic_phi_maker(vp, cp, onehots, prior, half_rho, cfg)
        res = lbfgs.lbfgs_solve(fun, x0, max_iters=cfg.lbfgs_iters,
                                use_line_search=True, phi_maker=pm)
        # n_iters rides along for the telemetry path; it is part of the
        # while_loop carry either way, so the non-collecting program DCEs
        # it without changing any computed value
        return res.x, res.loss, res.n_iters

    batch_solve = jax.vmap(jax.vmap(inner_solve))        # over (Nf, Ts)

    x_shape = (Nf, Ts, K * 2 * N * 2 * 2)
    init_iters_total = jnp.asarray(0, jnp.int32)
    if not warm and cfg.init_iters > 0:
        # chi2-only initialization at the per-subband data optimum
        def init_solve(x0, vp, cp, prior):
            zero_rho = jnp.zeros_like(half_rho)
            fun = lambda x: _cost_fn_onehot(x, vp, cp, onehots, prior,
                                            zero_rho, cfg)
            pm = _quartic_phi_maker(vp, cp, onehots, prior, zero_rho, cfg)
            res = lbfgs.lbfgs_solve(fun, x0, max_iters=cfg.init_iters,
                                    phi_maker=pm)
            return res.x, res.n_iters

        pr0 = J0.reshape((Nf, Ts, K, 2 * N, 2, 2))
        x_init, init_nit = jax.vmap(jax.vmap(init_solve))(
            J0.reshape(x_shape), Vp, Cp, pr0)
        J0 = x_init.reshape(J0.shape)
        if collect_stats:
            init_iters_total = jnp.sum(init_nit).astype(jnp.int32)
            if axis_name is not None:
                init_iters_total = lax.psum(init_iters_total, axis_name)

    rho6 = rho[None, None, :, None, None, None]

    def body(i, state):
        J, Y, Z, cost = state[:4]
        prior = _bz(bfull, Z) - Y / rho6
        x0 = J.reshape(x_shape)
        pr = prior.reshape((Nf, Ts, K, 2 * N, 2, 2))
        x, cost, nit = batch_solve(x0, Vp, Cp, pr)
        J = x.reshape(J.shape)
        Z = _z_update(bfull, Bi, rho, J, Y, axis_name)
        r = J - _bz(bfull, Z)
        Y = Y + rho6 * r
        if not collect_stats:
            return J, Y, Z, cost
        # telemetry: consensus RMS + inner-iteration total, additional
        # reductions over intermediates the update already computed
        rss = jnp.sum(r * r)
        nel = jnp.asarray(r.size, r.dtype)
        nit_sum = jnp.sum(nit)
        if axis_name is not None:
            rss = lax.psum(rss, axis_name)
            nel = lax.psum(nel, axis_name)
            nit_sum = lax.psum(nit_sum, axis_name)
        # mode="drop": an over-config admm_iters override (fuzzy env)
        # must drop the excess entries, never clamp onto the last slot
        pr_hist = state[4].at[i].set(jnp.sqrt(rss / nel), mode="drop")
        it_hist = state[5].at[i].set(nit_sum.astype(jnp.int32),
                                     mode="drop")
        return J, Y, Z, cost, pr_hist, it_hist

    Y0 = jnp.zeros_like(J0)
    Z0 = _z_update(bfull, Bi, rho, J0, Y0, axis_name)
    cost0 = jnp.zeros((Nf, Ts), J0.dtype)
    stats = None
    if collect_stats:
        init = (J0, Y0, Z0, cost0,
                jnp.zeros((cfg.admm_iters,), J0.dtype),
                jnp.zeros((cfg.admm_iters,), jnp.int32))
        J, Y, Z, cost, pr_hist, it_hist = lax.fori_loop(0, niter, body, init)
        stats = SolverStats(
            admm_iters=jnp.asarray(niter, jnp.int32),
            primal_resid=pr_hist, inner_iters=it_hist,
            init_iters=init_iters_total,
            n_segments=jnp.asarray(1, jnp.int32))
    else:
        J, Y, Z, cost = lax.fori_loop(0, niter, body, (J0, Y0, Z0, cost0))

    residual, sigma_res, sigma_data, fcost = _finalize(
        J, V6, C7, data_scale, cost, cfg, T, axis_name)
    return SolveResult(J=J, Z=Z, residual=residual, sigma_res=sigma_res,
                       sigma_data=sigma_data, final_cost=fcost, stats=stats)


# ---------------------------------------------------------------------------
# Host-segmented solve: identical math, bounded device dispatches
# ---------------------------------------------------------------------------
#
# solve_admm fuses init + the whole ADMM loop into ONE XLA program.  At
# LOFAR scale (N=62, Nf=8, init 30 + 10x8 L-BFGS iterations) that program
# runs for minutes on one chip — long enough to trip device/RPC-tunnel
# watchdogs (observed on the axon TPU tunnel as "UNAVAILABLE: TPU device
# error ... kernel fault"; N=62 with few iterations runs fine, N=40 with
# the full count faults).  The host-segmented driver below runs the SAME
# math as a sequence of bounded jitted calls: L-BFGS init in exact-resume
# segments (ops/lbfgs.lbfgs_resume) and one dispatch per ADMM outer
# iteration.  Numerics match solve_admm to float tolerance (identical op
# sequence; only XLA fusion boundaries differ) — tests/test_cal_backend.py
# asserts it.

@partial(jax.jit, static_argnames=("cfg", "iters", "init_phase"),
         donate_argnames=("x0",))
def _seg_start(x0, V6, C7, prior, rho, cfg, iters, init_phase):
    """Open a vmapped (Nf, Ts) L-BFGS solve for ``iters`` iterations;
    init_phase drops the consensus prior term (chi2-only).

    ``x0`` (the (Nf, Ts, K*2N*2*2) solution carry) is DONATED: the driver
    never reads the pre-segment iterate again, so on accelerators the
    output state reuses its HBM instead of allocating a fresh buffer per
    segment (no-op on CPU, where donation is unsupported)."""
    half_rho = jnp.zeros_like(rho) if init_phase else 0.5 * rho
    Vp, Cp = _eval_operands(V6, C7)
    onehots = _baseline_onehots(cfg.n_stations, V6.dtype)

    def one(x, vp, cp, pr):
        fun = lambda xx: _cost_fn_onehot(xx, vp, cp, onehots, pr,
                                         half_rho, cfg)
        pm = _quartic_phi_maker(vp, cp, onehots, pr, half_rho, cfg)
        return lbfgs.lbfgs_solve(fun, x, max_iters=iters,
                                 use_line_search=True, phi_maker=pm)

    return jax.vmap(jax.vmap(one))(x0, Vp, Cp, prior)


@partial(jax.jit, static_argnames=("cfg", "iters", "init_phase"),
         donate_argnames=("res",))
def _seg_resume(res, V6, C7, prior, rho, cfg, iters, init_phase):
    """Resume segment: the incoming L-BFGS state ``res`` (x, gradient,
    curvature history — the big per-segment carry) is DONATED into the
    outgoing state of identical structure, so segment N+1's state
    overwrites segment N's buffers in place on accelerators instead of
    doubling the carry footprint at every dispatch."""
    half_rho = jnp.zeros_like(rho) if init_phase else 0.5 * rho
    Vp, Cp = _eval_operands(V6, C7)
    onehots = _baseline_onehots(cfg.n_stations, V6.dtype)

    def one(r, vp, cp, pr):
        fun = lambda xx: _cost_fn_onehot(xx, vp, cp, onehots, pr,
                                         half_rho, cfg)
        pm = _quartic_phi_maker(vp, cp, onehots, pr, half_rho, cfg)
        return lbfgs.lbfgs_resume(fun, r, iters, phi_maker=pm)

    return jax.vmap(jax.vmap(one))(res, Vp, Cp, prior)


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("Y",))
def _host_consensus(J, Y, bfull, Bi, rho, cfg):
    """Z and dual updates after an outer iteration's inner solves (the
    shared _z_update/_bz formulas, one bounded dispatch).  The dual ``Y``
    — a full (Nf, Ts, K, 2N, 2, 2) consensus buffer — is donated into
    its own update (in-place on accelerators)."""
    Z = _z_update(bfull, Bi, rho, J, Y)
    Y = Y + rho[None, None, :, None, None, None] * (J - _bz(bfull, Z))
    return Z, Y, _bz(bfull, Z) - Y / rho[None, None, :, None, None, None]


_host_finalize = partial(jax.jit, static_argnames=("cfg", "T"))(_finalize)


@jax.jit
def _primal_resid_rms(J, Z, bfull):
    """Consensus RMS ||J - B Z|| — the host driver's telemetry probe, a
    SEPARATE tiny dispatch so the production host path stays untouched
    (bit-identical) when stats are off."""
    r = J - _bz(bfull, Z)
    return jnp.sqrt(jnp.mean(r * r))


def solve_admm_host(V, C, freqs, f0, rho, cfg: SolverConfig,
                    n_chunks: int = 1, admm_iters: Optional[int] = None,
                    freq_range=None, seg_iters: int = 8,
                    collect_stats: bool = False) -> SolveResult:
    """``solve_admm`` as bounded host-driven dispatches (single host/device;
    for the sharded multi-device path use parallel.sharded_cal, whose
    shard_map programs keep per-dispatch work 1/n-th the size anyway).

    seg_iters : max L-BFGS iterations per device dispatch.  The inner
        ADMM solves (cfg.lbfgs_iters each) are also segmented when
        cfg.lbfgs_iters > seg_iters.  Cold start only (J0 warm start is a
        solve_admm feature the radio envs don't use with host
        segmentation).

    collect_stats : fill ``result.stats`` with the segment count, the
        per-outer-iteration consensus residual (via a separate tiny
        dispatch, :func:`_primal_resid_rms`) and L-BFGS iteration totals.
        The production dispatch sequence is untouched either way.
    """
    Nf = V.shape[0]
    T = V.shape[1]
    K, N = cfg.n_dirs, cfg.n_stations
    Ts = n_chunks
    niter = cfg.admm_iters if admm_iters is None else int(admm_iters)
    if cfg.polytype == 1 and freq_range is None:
        fr = np.asarray(freqs)
        freq_range = (float(fr.min()), float(fr.max()))

    V6, C7, rho_n, data_scale, bfull, Bi = _prep(
        jnp.asarray(V), jnp.asarray(C), jnp.asarray(freqs), f0, rho, cfg,
        Ts, freq_range, axis_name=None)

    eye = jnp.zeros((2, 2, 2)).at[:, :, 0].set(jnp.eye(2))
    J0 = jnp.broadcast_to(eye, (Nf, Ts, K, N, 2, 2, 2)).reshape(
        Nf, Ts, K, 2 * N, 2, 2)
    x_shape = (Nf, Ts, K * 2 * N * 2 * 2)

    n_segments = 0

    def segmented_solve(x0, prior, total, init_phase):
        """total L-BFGS iterations as ceil(total/seg_iters) dispatches."""
        nonlocal n_segments
        first = min(seg_iters, total)
        res = _seg_start(x0, V6, C7, prior, rho_n, cfg, first, init_phase)
        jax.block_until_ready(res.x)
        n_segments += 1
        done = first
        while done < total:
            step = min(seg_iters, total - done)
            res = _seg_resume(res, V6, C7, prior, rho_n, cfg, step,
                              init_phase)
            jax.block_until_ready(res.x)
            n_segments += 1
            done += step
        return res

    init_iters_done = 0
    # chi2-only init phase (solve_admm's init_iters)
    if cfg.init_iters > 0:
        pr0 = J0.reshape((Nf, Ts, K, 2 * N, 2, 2))
        res = segmented_solve(J0.reshape(x_shape), pr0, cfg.init_iters,
                              init_phase=True)
        J0 = res.x.reshape(J0.shape)
        if collect_stats:
            init_iters_done = int(np.sum(np.asarray(res.n_iters)))

    Y = jnp.zeros_like(J0)
    Z = _z_update(bfull, Bi, rho_n, J0, Y)
    J = J0
    prior = _bz(bfull, Z) - Y / rho_n[None, None, :, None, None, None]
    cost = jnp.zeros((Nf, Ts), J0.dtype)
    # sized by the ACTUAL outer iteration count (niter is a host int here,
    # and callers like the fuzzy demixing env pass admm_iters overrides
    # above cfg.admm_iters — cfg-sized arrays would index out of bounds)
    pr_hist = np.zeros(niter, np.float32)
    it_hist = np.zeros(niter, np.int32)
    for it in range(niter):
        res = segmented_solve(J.reshape(x_shape),
                              prior.reshape((Nf, Ts, K, 2 * N, 2, 2)),
                              cfg.lbfgs_iters, init_phase=False)
        J, cost = res.x.reshape(J.shape), res.loss
        Z, Y, prior = _host_consensus(J, Y, bfull, Bi, rho_n, cfg)
        if collect_stats:
            pr_hist[it] = float(_primal_resid_rms(J, Z, bfull))
            it_hist[it] = int(np.sum(np.asarray(res.n_iters)))

    stats = None
    if collect_stats:
        stats = SolverStats(
            admm_iters=np.int32(niter), primal_resid=pr_hist,
            inner_iters=it_hist, init_iters=np.int32(init_iters_done),
            n_segments=np.int32(n_segments))
    residual, sigma_res, sigma_data, fcost = _host_finalize(
        J, V6, C7, data_scale, cost, cfg, T)
    return SolveResult(J=J, Z=Z, residual=residual, sigma_res=sigma_res,
                       sigma_data=sigma_data, final_cost=fcost, stats=stats)


class SolverDegradedError(RuntimeError):
    """Every degradation rung (rho-boosted retries, host-segmented
    fallback) still produced non-finite solutions — the one error the
    graceful-degradation ladder surfaces."""


def result_finite(res: SolveResult) -> bool:
    """Host check: are the solve's consensus iterates and residuals all
    finite?  One tiny reduction + device->host sync."""
    ok = (jnp.all(jnp.isfinite(res.J))
          & jnp.all(jnp.isfinite(res.residual))
          & jnp.all(jnp.isfinite(res.final_cost)))
    return bool(jax.device_get(ok))


def solve_admm_safe(solve_fn, rho, *, initial_result=None,
                    host_fallback=None, max_retries: int = 2,
                    rho_boost: float = 10.0, on_event=None):
    """Graceful degradation around ANY solve route: detect non-finite
    consensus iterates and walk the recovery ladder instead of handing a
    poisoned result downstream.

    1. ``solve_fn(rho)`` (or the caller's already-computed
       ``initial_result``) — the production route, untouched when the
       solve is healthy;
    2. up to ``max_retries`` re-solves at ``rho * rho_boost**attempt``
       (a diverging consensus usually means the regularization was too
       weak for the drawn scene; boosting rho contracts the inner
       problem);
    3. ``host_fallback(rho)`` — the host-segmented route, whose bounded
       dispatches sidestep fused-program pathologies;
    4. :class:`SolverDegradedError`.

    Returns ``(result, info)`` where ``info`` records what happened
    ({"degraded", "attempts", "route", "rho_scale"}); ``on_event`` (if
    given) is called with the same fields per degradation step — the
    caller's RunLog hook, so this module stays obs-free.
    """
    rho = jnp.asarray(rho)
    info = {"degraded": False, "attempts": 0, "route": "primary",
            "rho_scale": 1.0}
    res = initial_result if initial_result is not None else solve_fn(rho)
    if result_finite(res):
        return res, info
    info["degraded"] = True
    for attempt in range(1, max_retries + 1):
        scale = float(rho_boost) ** attempt
        info.update(attempts=attempt, route="retry_rho", rho_scale=scale)
        if on_event is not None:
            on_event(**info)
        res = solve_fn(rho * scale)
        if result_finite(res):
            return res, info
    if host_fallback is not None:
        info.update(route="host_segmented", rho_scale=1.0)
        if on_event is not None:
            on_event(**info)
        res = host_fallback(rho)
        if result_finite(res):
            return res, info
    tail = (" and the host-segmented fallback"
            if host_fallback is not None else "")
    raise SolverDegradedError(
        f"non-finite ADMM iterates survived {info['attempts']} rho-boosted "
        f"retries (x{rho_boost}){tail}")


def simulate_vis_sr(J, C, n_stations, Ts):
    """Corrupt model coherencies with per-interval Jones: the in-framework
    stand-in for ``sagecal_gpu -O DATA -p ...`` simulation
    (generate_data.py:1226-1228).

    J : (Ts, K, 2N, 2, 2); C : (K, T*B, 4, 2) kernel convention.
    Returns (T, B, 2, 2, 2).
    """
    B_count = n_stations * (n_stations - 1) // 2
    C6 = coherency_to_chunks(C, B_count, Ts)             # (Ts, K, td, B, ...)
    V = jax.vmap(lambda j, c: predict_vis_sr(j, c, n_stations))(J, C6)
    return V.reshape(-1, B_count, 2, 2, 2)


@partial(jax.jit, static_argnames=("n_stations", "Ts"))
def simulate_vis_multi_sr(J, C, n_stations, Ts):
    """All-sub-band :func:`simulate_vis_sr` in ONE dispatch.

    J : (Nf, Ts, K, 2N, 2, 2); C : (Nf, K, T*B, 4, 2).
    Returns (Nf, T, B, 2, 2, 2) — the vmapped form of the envs'
    per-frequency corruption loop (O(Nf) dispatches -> O(1))."""
    return jax.vmap(
        lambda j, c: simulate_vis_sr(j, c, n_stations, Ts))(J, C)


def residual_to_kernel(residual):
    """(T, B, 2, 2, 2) solver residual -> kernel-convention R (2BT, 2, 2):
    sample ck = t*B + b occupies rows 2ck:2ck+2 (see cal/kernels.py)."""
    T, B = residual.shape[0], residual.shape[1]
    return residual.reshape(T * B, 2, 2, 2).reshape(2 * T * B, 2, 2)


def stokes_i_std(V):
    """Noise proxy: std of Stokes I = (XX + YY)/2 real/imag planes, the
    statistic the demixing env reads from the MS (demixingenv.py:233-252)."""
    sI = 0.5 * (V[..., 0, 0, :] + V[..., 1, 1, :])
    return jnp.std(sI)


def cost_eval_flops(cfg: SolverConfig, Nf: int, Ts: int, td: int, B: int):
    """XLA-counted FLOPs of the solver's inner evaluation units.

    Cross-checks the analytic FLOP model that ``bench.py`` quotes MFU
    from (VERDICT r4 item 5): lower the EXACT batched evaluation
    functions the L-BFGS driver runs — the vmapped ``value_and_grad``
    of ``_cost_fn_onehot`` (one per iteration) and the quartic
    line-search coefficient build (`_quartic_phi_maker`, four bilinear
    model evaluations once per iteration; the probes themselves are
    O(1)) — and read ``compiled.cost_analysis()['flops']``.  Shape-only
    (``ShapeDtypeStruct``) on the CPU backend: no data, no execution,
    and never a chip-side compile; HLO flop counting is semantic, so
    the CPU-lowered count validates the model for the TPU run too
    (the model's stated accuracy target is ~2x, not profiler-grade).

    Whole-loop ``cost_analysis`` is useless here — it counts a
    ``while_loop`` body ONCE — which is exactly why the per-eval unit
    is measured and the iteration count stays analytic.

    Returns a dict: xla_* counts, model_* counts (112 flop/sample/dir
    forward unit; x3 reverse-mode; x2 jvp), and their ratios.
    """
    K, N = cfg.n_dirs, cfg.n_stations
    f32 = jnp.float32
    sd = jax.ShapeDtypeStruct
    x = sd((Nf, Ts, K * 2 * N * 2 * 2), f32)
    d = sd((Nf, Ts, K * 2 * N * 2 * 2), f32)
    alpha = sd((Nf, Ts), f32)
    # the production eval consumes pre-transposed planes operands
    # (_eval_operands layout) with the one-hot station expansion
    v5 = sd((Nf, Ts, 2, 2, 2, td, B), f32)
    c5 = sd((Nf, Ts, K, 2, 2, 2, td, B), f32)
    pr = sd((Nf, Ts, K, 2 * N, 2, 2), f32)
    hr = sd((K,), f32)
    onehots = _baseline_onehots(N)

    def vag_one(xx, v, c, p, h):
        return jax.value_and_grad(
            lambda q: _cost_fn_onehot(q, v, c, onehots, p, h, cfg))(xx)

    def setup_one(xx, dd, aa, v, c, p, h):
        # the production line search: build the quartic coefficients
        # (four bilinear model evals, see _quartic_phi_maker) and take
        # one (O(1)) probe
        pm = _quartic_phi_maker(v, c, onehots, p, h, cfg)
        return pm(None, xx, dd)(aa)

    lanes2 = ((0, 0, 0, 0, None), (0, 0, 0, 0, 0, 0, None))

    def _flops(fn, in_axes, *avals):
        f = jax.vmap(jax.vmap(fn, in_axes=in_axes), in_axes=in_axes)
        # pin lowering to an explicit CPU device: the jit(backend="cpu")
        # kwarg this used is removed in newer JAX; default_device steers
        # the shape-only lower+compile the same way on every pin, and
        # never initializes the (possibly wedged-tunnel) TPU backend
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            compiled = jax.jit(f).lower(*avals).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return float((ca or {}).get("flops", float("nan")))

    xla_vag = _flops(vag_one, lanes2[0], x, v5, c5, pr, hr)
    xla_setup = _flops(setup_one, lanes2[1], x, d, alpha, v5, c5, pr, hr)
    model_cost = 112.0 * K * Nf * Ts * td * B
    out = {
        "xla_value_and_grad_flops": xla_vag,
        "xla_linesearch_setup_flops": xla_setup,
        "model_value_and_grad_flops": 3.0 * model_cost,
        # four bilinear model evaluations since the exact-P1 fix
        # (m0, m2, and the two mixed terms — see _quartic_phi_maker)
        "model_linesearch_setup_flops": 4.0 * model_cost,
        "counted_on": "cpu-backend HLO cost_analysis",
    }
    if np.isfinite(xla_vag) and xla_vag > 0:
        out["vag_model_over_xla"] = round(3.0 * model_cost / xla_vag, 3)
    if np.isfinite(xla_setup) and xla_setup > 0:
        out["setup_model_over_xla"] = round(4.0 * model_cost / xla_setup,
                                            3)
    return out
