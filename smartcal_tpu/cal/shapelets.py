"""Shapelet (Gauss-Hermite) diffuse-sky models.

Reference: the diffuse-sky option of the simulator writes random shapelet
mode files for SAGECal to predict (``calibration/simulate.py:360-383``,
``calibration_tools.py:1254-1295`` generate_random_shapelet_model,
``correct_shapelet_modes.py`` factorial rescale).  The prediction itself
happens inside SAGECal there; in-framework it is done analytically here —
the shapelet basis is (up to i^n) its own Fourier transform, so the uv-plane
coherency of a diffuse component is a closed-form sum that lands on the MXU
as one (modes x samples) matmul, no gridding needed.

Conventions (matching cal/coherency's e^{+i phase} prediction):
  image basis   phi_n(x; b) = H_n(x/b) exp(-x^2/(2 b^2))
                              / sqrt(2^n n! sqrt(pi) b)
  visibility    V(u, v) = 2 pi sum_{n1, n2} a_{n1 n2} i^{n1+n2}
                          phi_{n1}(2 pi u_l; 1/b) phi_{n2}(2 pi v_l; 1/b)
with u_l, v_l in wavelengths; this is the exact continuous FT of
I(l, m) = sum a phi phi under V = int I e^{+2 pi i (u l + v m)} dl dm
(golden-tested against a direct numpy grid integration).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


def basis_1d(n_max: int, x, beta):
    """phi_0..phi_{n_max-1} at ``x``: (n_max, ...) orthonormal basis.

    Evaluated via the recurrence on the NORMALIZED Hermite functions
    psi_{n+1} = t sqrt(2/(n+1)) psi_n - sqrt(n/(n+1)) psi_{n-1} with the
    Gaussian envelope folded in from the start — the raw H_n(t) recurrence
    overflows float32 at the large uv arguments of resolved-out baselines
    (H_19(1e4) = inf, then inf * exp(-t^2/2) = nan), while psi_n stays
    bounded and underflows cleanly to 0 there.
    """
    t = jnp.asarray(x) / beta
    env = jnp.exp(-0.5 * t * t)
    psi = [env * (math.pi ** -0.25)]
    if n_max > 1:
        psi.append(t * math.sqrt(2.0) * psi[0])
    for n in range(1, n_max - 1):
        psi.append(t * math.sqrt(2.0 / (n + 1)) * psi[n]
                   - math.sqrt(n / (n + 1.0)) * psi[n - 1])
    return jnp.stack(psi[:n_max]) / jnp.sqrt(beta)


def shapelet_image(coeff, l, m, beta, l0=0.0, m0=0.0):
    """I(l, m) = sum a_{n1 n2} phi_{n1}(l - l0) phi_{n2}(m - m0)."""
    coeff = jnp.asarray(coeff)
    n0 = coeff.shape[0]
    bl = basis_1d(n0, jnp.asarray(l) - l0, beta)          # (n0, ...)
    bm = basis_1d(n0, jnp.asarray(m) - m0, beta)
    return jnp.einsum("ab,a...,b...->...", coeff, bl, bm)


def shapelet_uv_sr(coeff, u_l, v_l, beta, l0=0.0, m0=0.0):
    """Split-real visibilities (..., 2) of the shapelet at baseline
    coordinates ``u_l, v_l`` (wavelengths).

    The i^{n1+n2} factor routes each mode into one of (+Re, +Im, -Re, -Im);
    an off-center component picks up the usual e^{+2 pi i (u l0 + v m0)}
    phase ramp.
    """
    coeff = jnp.asarray(coeff, jnp.float32)
    n0 = coeff.shape[0]
    ku = 2.0 * jnp.pi * jnp.asarray(u_l)
    kv = 2.0 * jnp.pi * jnp.asarray(v_l)
    bu = basis_1d(n0, ku, 1.0 / beta)                     # (n0, R)
    bv = basis_1d(n0, kv, 1.0 / beta)
    prod = jnp.einsum("ab,a...,b...->ab...", coeff, bu, bv)
    n_sum = np.add.outer(np.arange(n0), np.arange(n0)) % 4
    # i^n: n=0 -> +Re, 1 -> +Im, 2 -> -Re, 3 -> -Im
    re_w = jnp.asarray(np.where(n_sum == 0, 1.0, 0.0)
                       - np.where(n_sum == 2, 1.0, 0.0), jnp.float32)
    im_w = jnp.asarray(np.where(n_sum == 1, 1.0, 0.0)
                       - np.where(n_sum == 3, 1.0, 0.0), jnp.float32)
    sp = (2.0 * jnp.pi)
    re = sp * jnp.einsum("ab,ab...->...", re_w, prod)
    im = sp * jnp.einsum("ab,ab...->...", im_w, prod)
    phase = 2.0 * jnp.pi * (jnp.asarray(u_l) * l0 + jnp.asarray(v_l) * m0)
    c, s = jnp.cos(phase), jnp.sin(phase)
    return jnp.stack([re * c - im * s, re * s + im * c], axis=-1)


def shapelet_coherency_sr(coeff, uu, vv, freq, beta, flux=1.0,
                          l0=0.0, m0=0.0):
    """(R, 4, 2) coherency contribution of a Stokes-I shapelet component:
    V in XX and YY (the cluster convention of cal/coherency._predict),
    scaled by the sky-table flux.  ``uu, vv`` in meters."""
    C_LIGHT = 299792458.0
    scale = freq / C_LIGHT
    vis = flux * shapelet_uv_sr(coeff, jnp.asarray(uu) * scale,
                                jnp.asarray(vv) * scale, beta,
                                l0=l0, m0=m0)
    R = vis.shape[0]
    C = jnp.zeros((R, 4, 2), jnp.float32)
    C = C.at[:, 0, :].set(vis)
    C = C.at[:, 3, :].set(vis)
    return C


@jax.jit
def _shapelet_coherency_multi(coeff, uu, vv, scales, beta, flux, l0, m0):
    def one(s):
        vis = flux * shapelet_uv_sr(coeff, uu * s, vv * s, beta,
                                    l0=l0, m0=m0)
        R = vis.shape[0]
        C = jnp.zeros((R, 4, 2), jnp.float32)
        return C.at[:, 0, :].set(vis).at[:, 3, :].set(vis)

    return jax.vmap(one)(scales)


def shapelet_coherency_multi_sr(coeff, uu, vv, freqs, beta, flux=1.0,
                                l0=0.0, m0=0.0):
    """(Nf, R, 4, 2) shapelet coherencies for ALL sub-bands in one
    dispatch — the vmapped form of :func:`shapelet_coherency_sr`, with
    the per-band wavelength scales rounded on host exactly like the
    single-band wrapper so the two paths agree to float round-off."""
    C_LIGHT = 299792458.0
    scales = jnp.asarray(np.asarray(freqs, np.float64) / C_LIGHT,
                         jnp.float32)
    return _shapelet_coherency_multi(jnp.asarray(coeff, jnp.float32),
                                     jnp.asarray(uu), jnp.asarray(vv),
                                     scales, beta, flux, l0, m0)


class ShapeletModel(NamedTuple):
    """A random diffuse component + its perturbed calibration twin
    (simulate.py:365-377 writes exact modes for simulation and a perturbed
    file for the calibration model)."""

    coeff: np.ndarray         # (n0, n0)
    beta: float
    coeff_cal: np.ndarray
    beta_cal: float
    l0: float = 0.0
    m0: float = 0.0
    flux: float = 250.0       # sky-table Stokes I (simulate.py:366)


def random_shapelet(rng, perturb: bool = True) -> ShapeletModel:
    """Random modes with the reference's statistics
    (calibration_tools.py:1256-1271): n0 in [10, 20), beta = U + 0.1
    capped so n0*beta ~ 2, N(0,1) coefficients attenuated by
    (outer(1..n0, 1..n0))^1.2; the perturbed twin adds 10% beta noise and
    10%-norm coefficient noise (:1281-1294)."""
    n0 = int(rng.integers(10, 20))
    beta = float(rng.random() + 0.1)
    if beta * n0 > 2:
        beta = float((2 + rng.random() * 0.001) / n0)
    x = np.arange(1, n0 + 1)
    coeff = rng.standard_normal((n0, n0)) / np.outer(x, x) ** 1.2
    if perturb:
        beta_cal = beta + 0.1 * beta * rng.random()
        noise = rng.standard_normal((n0, n0))
        noise = noise / np.linalg.norm(noise) * 0.1 * np.linalg.norm(coeff)
        coeff_cal = coeff + noise
    else:
        beta_cal, coeff_cal = beta, coeff.copy()
    return ShapeletModel(coeff=coeff.astype(np.float32), beta=beta,
                         coeff_cal=coeff_cal.astype(np.float32),
                         beta_cal=float(beta_cal))


def write_modes(path, coeff, beta, radec=(0, 0, 0.0, 0, 0, 0.0)):
    """SAGECal ``.modes`` text writer (generate_random_shapelet_model
    format: sexagesimal position line, 'n0 beta', n0^2 'idx value' lines,
    linear-transform line)."""
    coeff = np.asarray(coeff)
    n0 = coeff.shape[0]
    flat = coeff.reshape(-1)
    with open(path, "w") as fh:
        fh.write(" ".join(str(v) for v in radec) + "\n")
        fh.write(f"{n0} {beta}\n")
        for ci in range(n0 * n0):
            fh.write(f"{ci} {flat[ci]}\n")
        fh.write(f"L 1.0 1.0 {math.pi / 2}\n")
        fh.write("#model created by smartcal_tpu\n")


def read_modes(path):
    """Inverse of :func:`write_modes` -> (coeff (n0, n0), beta)."""
    with open(path) as fh:
        lines = [ln.strip() for ln in fh if ln.strip()
                 and not ln.startswith("#")]
    n0, beta = lines[1].split()
    n0, beta = int(n0), float(beta)
    vals = np.zeros(n0 * n0, np.float32)
    for ln in lines[2:2 + n0 * n0]:
        idx, v = ln.split()
        vals[int(idx)] = float(v)
    return vals.reshape(n0, n0), beta


def rescale_modes(coeff):
    """Old->new SAGECal mode convention (correct_shapelet_modes.py:6-30):
    value * ci!/(ci+1)! * cj!/(cj+1)! = value / ((ci+1)(cj+1))."""
    coeff = np.asarray(coeff)
    n0 = coeff.shape[0]
    i = np.arange(n0) + 1.0
    return coeff / np.outer(i, i)
