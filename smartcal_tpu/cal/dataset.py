"""Real-observation featurization: MS files -> transformer input vector.

Reference: ``calibration/generate_data.py:696-873`` (get_info_from_dataset)
— the path that lets the trained demixing recommender run on REAL LOFAR
data: extract + average a time slice of an observation, calibrate it against
the A-team + target sky, compute per-direction influence maps, and assemble
the K x (Ninf^2 + 8) feature vector the transformer was trained on.

The reference chains five external programs (DP3, LINC sky download,
sagecal-mpi, writecorr, excon/wsclean); here every stage is in-framework:

  extract_dataset      -> cal.ms_io.extract_dataset   (host numpy)
  sagecal-mpi          -> cal.solver.solve_admm        (jit, TPU)
  analysis_uvw_perdir  -> cal.influence                (jit, TPU)
  excon imaging        -> cal.imager.dirty_image_sr    (jit, TPU)
  LINC target download -> point-source stand-in or a user-supplied sky/
                          cluster file parsed by cal.skyio (zero egress)

:func:`assemble_features` is the SINGLE feature-assembly implementation,
shared with the synthetic training-data generator
(``train.supervised.generate_training_data``) so train-time and eval-time
features cannot drift apart.
"""

from __future__ import annotations

import math
import os
from typing import List, NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from smartcal_tpu.cal import (coherency, coords, creal, imager,
                              influence as influence_mod, ms_io,
                              observation as obs_mod, simulate, skyio,
                              solver)


# The raw likelihood-ratio statistic is unnormalized (reference
# calibration_tools.py:1217-1222: ||r+mu||^2 - ||r||^2 over the Stokes-V
# noise estimate, no sample-count division) and under strong sky-model
# mismatch reaches |LLR| ~ 1e8 — enough to overflow a float32 transformer
# forward.  Well-matched models give |LLR| <~ 1e3 (the training
# distribution), so saturating at 1e4 only affects the pathological tail.
LLR_CLIP = 1e4


def assemble_features(inf_vis, summary, uvw, freqs, sep, az, el, npix):
    """K x (npix^2 + 8) feature vector (generate_data.py:835-858).

    Per direction ck: the Stokes-I influence visibilities imaged to npix^2
    (Fortran-flattened, L2-normalized like the reference's
    ``x /= imgnorm``), then [separation, azimuth, elevation, log||J||,
    log||C||, log|Inf|, LLR (clipped, see ``LLR_CLIP``), log f_0].
    """
    freqs = np.asarray(freqs)
    uvw = jnp.asarray(np.asarray(uvw).reshape(-1, 3))
    cell = imager.default_cell(uvw[None], float(freqs[0]))
    K = inf_vis.shape[0]
    nout = npix * npix + 8
    x = np.zeros(K * nout, np.float32)
    for ck in range(K):
        ivis = influence_mod.stokes_i_influence(inf_vis[ck])
        img = np.asarray(imager.dirty_image_sr(uvw, ivis, float(freqs[0]),
                                               cell, npix=npix))
        flat = img.reshape(-1, order="F")
        flat = flat / max(np.linalg.norm(flat), 1e-12)
        o = ck * nout
        x[o:o + npix * npix] = flat
        x[o + npix * npix + 0] = sep[ck]
        x[o + npix * npix + 1] = az[ck]
        x[o + npix * npix + 2] = el[ck]
        x[o + npix * npix + 3] = np.log(max(float(summary.j_norm[ck]), 1e-12))
        x[o + npix * npix + 4] = np.log(max(float(summary.c_norm[ck]), 1e-12))
        x[o + npix * npix + 5] = np.log(max(float(summary.inf_mean[ck]),
                                            1e-12))
        x[o + npix * npix + 6] = float(np.clip(summary.llr_mean[ck],
                                               -LLR_CLIP, LLR_CLIP))
        x[o + npix * npix + 7] = np.log(freqs[0])
    return x


class CalSky(NamedTuple):
    """Calibration sky + per-cluster metadata for a pointing."""

    sky: object            # coherency.SkyArrays
    separations: np.ndarray   # deg, per cluster
    azimuth: np.ndarray
    elevation: np.ndarray
    rho: np.ndarray


DATA_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "data")


def ateam_paths():
    """Checked-in real A-team catalogue (CasA/CygA/HerA/TauA/VirA, 533
    sources in 5 clusters) — the reference's ``demixing/base.{sky,cluster,
    rho}`` converted through skyio by ``tools/convert_ateam.py``."""
    return (os.path.join(DATA_DIR, "ateam.sky"),
            os.path.join(DATA_DIR, "ateam.cluster"),
            os.path.join(DATA_DIR, "ateam.rho"))


_ATEAM_CENTER_CACHE: list = []


def _ateam_cluster_centers(K):
    """Per-cluster (ra, dec) centers of the first K-1 fixture clusters,
    from the unit-vector mean of member-source directions (the role of the
    reference's measures-based ``get_cluster_centers``,
    generate_data.py:789).  Cached: the checked-in fixture is immutable,
    and the real-data hot path calls this per featurization."""
    if not _ATEAM_CENTER_CACHE:
        sky_p, clus_p, _ = ateam_paths()
        S = skyio.parse_sky_model(sky_p)
        clusters = skyio.parse_cluster_file(clus_p)
        for _, names in clusters:
            info = np.stack([S[nm] for nm in names])
            ra = np.asarray(coords.hms_to_rad(info[:, 0], info[:, 1],
                                              info[:, 2]))
            dec = np.asarray([coords.dms_to_rad(*row[3:6]) for row in info])
            x = np.mean(np.cos(dec) * np.cos(ra))
            y = np.mean(np.cos(dec) * np.sin(ra))
            z = np.mean(np.sin(dec))
            _ATEAM_CENTER_CACHE.append(
                (math.atan2(y, x) % (2 * math.pi),
                 math.atan2(z, math.hypot(x, y))))
    return _ATEAM_CENTER_CACHE[:K - 1]


def _ateam_fixture_sky(ra0, dec0, lst0, f0, K, rho_path=None) -> CalSky:
    """Real-A-team default sky: the fixture's first K-1 clusters plus a
    unit point source at the phase center standing in for the LINC target
    download (generate_data.py:760-776 concatenates the converted target
    model with base.*; the download itself is out of scope, zero egress)."""
    sky_p, clus_p, rho_p = ateam_paths()
    full = skyio.build_sky_arrays(sky_p, clus_p, ra0, dec0)
    keep = np.asarray(full.cluster) < K - 1
    lmn = np.concatenate([np.asarray(full.lmn)[keep],
                          [[0.0, 0.0, 0.0]]])
    flux_coef = np.concatenate([np.asarray(full.flux_coef)[keep],
                                [[0.0, 0.0, 0.0, 0.0]]])   # log(1.0) target
    f0s = np.concatenate([np.asarray(full.f0)[keep], [f0]])
    gauss = np.concatenate([np.asarray(full.gauss)[keep],
                            [[0.0, 0.0, 0.0]]])
    is_gauss = np.concatenate([np.asarray(full.is_gauss)[keep], [False]])
    cluster = np.concatenate([np.asarray(full.cluster)[keep], [K - 1]])
    sky = coherency.SkyArrays(lmn=lmn, flux_coef=flux_coef, f0=f0s,
                              gauss=gauss, is_gauss=is_gauss,
                              cluster=cluster, n_clusters=K)

    sep, azl, ell = [], [], []
    for ra, dec in _ateam_cluster_centers(K):
        sep.append(math.degrees(float(
            coords.angular_separation(ra0, dec0, ra, dec))))
        az, el = coords.azel_from_radec(ra, dec, lst0, obs_mod.LOFAR_LAT)
        azl.append(math.degrees(float(az)))
        ell.append(math.degrees(float(el)))
    az0, el0 = coords.azel_from_radec(ra0, dec0, lst0, obs_mod.LOFAR_LAT)
    sep.append(0.0)
    azl.append(math.degrees(float(az0)))
    ell.append(math.degrees(float(el0)))

    if rho_path is None:
        rho_spec, _ = skyio.read_rho(rho_p, 5)
        rho = np.concatenate([np.asarray(rho_spec)[:K - 1], [10.0]])
    else:
        # a user rho file may carry K rows (incl. target) or K-1
        # outlier-only rows (fixture style: target rho defaults to 10.0)
        rows = len(skyio._data_lines(rho_path))
        if rows == K:
            rho = np.asarray(skyio.read_rho(rho_path, K)[0])
        elif rows == K - 1:
            rho_spec, _ = skyio.read_rho(rho_path, K - 1)
            rho = np.concatenate([np.asarray(rho_spec), [10.0]])
        else:
            raise ValueError(
                f"rho file {rho_path} has {rows} rows; expected K={K} "
                f"(incl. target) or K-1={K - 1} (outliers only)")
    return CalSky(sky, np.asarray(sep, np.float32),
                  np.asarray(azl, np.float32),
                  np.asarray(ell, np.float32),
                  np.asarray(rho, np.float32))


def assemble_real_sky(target_skymodel, outdir, num_patches=1):
    """The reference's real-data sky assembly (generate_data.py:760-776):
    convert a user-supplied DP3/makesourcedb TARGET model and concatenate
    it after the A-team fixture, target cluster(s) last.

    Returns ``(sky_path, cluster_path, rho_path, K)`` ready for
    :func:`get_info_from_dataset` — K = 5 A-team clusters + the target
    patches.  (The LINC download that produces ``target_skymodel`` is out
    of scope — zero egress; any DP3-format sky model works.)
    """
    at_sky, at_clus, at_rho = ateam_paths()
    tmp_sky = os.path.join(outdir, "target.sky")
    tmp_clus = os.path.join(outdir, "target.cluster")
    tmp_rho = os.path.join(outdir, "target.rho")
    n_target = skyio.convert_dp3_skymodel(
        target_skymodel, tmp_sky, tmp_clus, tmp_rho, start_cluster=6,
        num_patches=num_patches)
    out = []
    for base, tmp, name in ((at_sky, tmp_sky, "sky.txt"),
                            (at_clus, tmp_clus, "cluster.txt"),
                            (at_rho, tmp_rho, "admm_rho.txt")):
        dst = os.path.join(outdir, name)
        with open(dst, "w") as fh:
            for src in (base, tmp):
                with open(src) as sf:
                    fh.write(sf.read())
        out.append(dst)
    return out[0], out[1], out[2], 5 + n_target


def calibration_sky(ra0, dec0, t0, f0, K=6, sky_path=None,
                    cluster_path=None, rho_path=None, seed=0,
                    synthetic=False) -> CalSky:
    """Build the calibration sky for a real pointing.

    With ``sky_path``/``cluster_path`` the user supplies the full model
    (the role of the LINC download + base.sky concatenation,
    generate_data.py:760-776).  Otherwise the default is the REAL A-team
    catalogue fixture (``ateam_paths``) with a unit point source standing
    in for the target — matching the reference's real-data evaluation sky
    up to the downloaded target model.  ``synthetic=True`` selects the
    older synthesized stand-in (K-1 random A-team-like clusters), kept for
    tests and for K > 6.
    """
    lst0 = obs_mod.OMEGA_EARTH * t0 % (2 * math.pi)
    if (sky_path is None) != (cluster_path is None):
        raise ValueError(
            "sky_path and cluster_path must be given together — with only "
            "one, the synthetic stand-in sky would silently replace the "
            "user's model")
    if sky_path is not None and cluster_path is not None:
        sky = skyio.build_sky_arrays(sky_path, cluster_path, ra0, dec0)
        Kf = sky.n_clusters
        sep, azl, ell, flux = [], [], [], []
        for ci in range(Kf):
            sel = np.asarray(sky.cluster) == ci
            l = float(np.mean(np.asarray(sky.lmn)[sel, 0]))
            m = float(np.mean(np.asarray(sky.lmn)[sel, 1]))
            ra, dec = (float(v) for v in coords.lmtoradec(l, m, ra0, dec0))
            sep.append(math.degrees(float(
                coords.angular_separation(ra0, dec0, ra, dec))))
            az, el = coords.azel_from_radec(ra, dec, lst0,
                                            obs_mod.LOFAR_LAT)
            azl.append(math.degrees(float(az)))
            ell.append(math.degrees(float(el)))
            flux.append(float(np.sum(np.exp(
                np.asarray(sky.flux_coef)[sel, 0]))))
        if rho_path is not None:
            rho = skyio.read_rho(rho_path, Kf)[0]    # spectral column
        else:
            rho = 0.1 * np.asarray(flux, np.float32)
        return CalSky(sky, np.asarray(sep, np.float32),
                      np.asarray(azl, np.float32),
                      np.asarray(ell, np.float32),
                      np.asarray(rho, np.float32))

    n_ateam = K - 1
    if (not synthetic and n_ateam <= 5
            and os.path.exists(ateam_paths()[0])):
        return _ateam_fixture_sky(ra0, dec0, lst0, f0, K, rho_path=rho_path)

    if n_ateam > len(obs_mod.ATEAM_DIRS):
        raise ValueError(f"K={K} exceeds the {len(obs_mod.ATEAM_DIRS)}"
                         " A-team clusters of the fallback sky")
    import jax

    at = simulate.ateam_components(jax.random.PRNGKey(seed), ra0, dec0, f0)
    draw = simulate.SkyDraw()
    sep, azl, ell, rho = [], [], [], []
    for i in range(n_ateam):
        ra, dec = obs_mod.ATEAM_DIRS[i]
        sep.append(math.degrees(float(
            coords.angular_separation(ra0, dec0, ra, dec))))
        az, el = coords.azel_from_radec(ra, dec, lst0, obs_mod.LOFAR_LAT)
        azl.append(math.degrees(float(az)))
        ell.append(math.degrees(float(el)))
        atten = 0.05 + 0.95 * max(0.0, math.sin(max(float(el), 0.0))) ** 2
        draw.add(at.l[i], at.m[i], at.flux[i] * atten, at.sp[i], i)
        rho.append(obs_mod.ATEAM_FLUX[i] * atten * 0.1)
    # target: single point source at the phase center, unit apparent flux
    draw.add(np.zeros(1), np.zeros(1), np.ones(1), np.zeros(1), K - 1)
    az0, el0 = coords.azel_from_radec(ra0, dec0, lst0, obs_mod.LOFAR_LAT)
    sep.append(0.0)
    azl.append(math.degrees(float(az0)))
    ell.append(math.degrees(float(el0)))
    rho.append(10.0)
    return CalSky(draw.build(K, f0), np.asarray(sep, np.float32),
                  np.asarray(azl, np.float32), np.asarray(ell, np.float32),
                  np.asarray(rho, np.float32))


def _read_vis_sr(path, colname, B, n_times):
    """MS column -> ((T, B, 2, 2, 2) split-real, (T, B, 3) uvw)."""
    uu, vv, ww, xx, xy, yx, yy = ms_io.read_corr(path, colname)
    V = np.stack([xx, xy, yx, yy], axis=-1).reshape(-1, B, 2, 2)
    uvw = np.stack([uu, vv, ww], axis=-1).reshape(-1, B, 3)
    return creal.split(V[:n_times]), uvw[:n_times]


def get_info_from_dataset(mslist: List[str], timesec: float, Ninf: int = 64,
                          K: int = 6, Nf: int = 3, tdelta: int = 10,
                          sky_path: Optional[str] = None,
                          cluster_path: Optional[str] = None,
                          rho_path: Optional[str] = None,
                          n_poly: int = 2, admm_iters: int = 10,
                          lbfgs_iters: int = 8, init_iters: int = 30,
                          rng=None, workdir: str = ".",
                          synthetic: bool = False):
    """Featurize a ``timesec``-second slice of a real (or MS-shaped
    synthetic) observation for the demixing recommender.

    Returns the K x (Ninf^2 + 8) float32 vector of
    generate_data.py:835-858.  The MSs may be casacore MSs (when
    python-casacore is installed) or npz stores — both go through
    cal.ms_io transparently.  The calibration sky defaults to the real
    A-team fixture (see :func:`calibration_sky`); ``synthetic=True``
    selects the synthesized stand-in clusters instead.
    """
    rng = rng or np.random.default_rng(0)
    sub = ms_io.extract_dataset(mslist, timesec, Nf=Nf, rng=rng,
                                outdir=workdir)

    # normalize the data scale (generate_data.py:710-721): the solver and
    # the unit-flux target stand-in both want O(1) visibilities.  The
    # reference's sqrt(norm/size) is NOT scale-free (scaled RMS grows as
    # n^0.25 with observation size); unit-RMS normalization needs
    # norm / sqrt(size), used here so the flux-1.0 phase-center stand-in
    # stays correctly weighted at any data size.
    _, _, _, xx, xy, yx, yy = ms_io.read_corr(sub[0], "DATA")
    d = np.stack([xx, xy, yx, yy])
    scalefac = float(np.linalg.norm(d) / np.sqrt(d.size))
    for ms in sub:
        u1, v1, w1, *corr = ms_io.read_corr(ms, "DATA")
        ms_io.write_corr(ms, *(c / scalefac for c in corr), colname="DATA")

    info = ms_io.ms_info(sub[0])
    N, B = info.n_stations, info.n_baselines
    Ts = max(1, info.n_times // tdelta)
    n_times = Ts * tdelta
    if info.n_times < tdelta:
        # fewer slots than one solution interval: shrink the interval
        tdelta, Ts, n_times = info.n_times, 1, info.n_times
    freqs = np.asarray([ms_io.ms_info(ms).freqs[0] for ms in sub],
                       np.float64)
    f0 = float(freqs.mean())

    cal = calibration_sky(info.ra0, info.dec0, info.t0, f0, K=K,
                          sky_path=sky_path, cluster_path=cluster_path,
                          rho_path=rho_path, synthetic=synthetic)
    if cal.sky.n_clusters != K:
        # a user-supplied cluster file must match the trained model's K —
        # a silent override would only surface as an opaque Dense-kernel
        # shape error deep inside model.apply
        raise ValueError(
            f"cluster file defines {cal.sky.n_clusters} directions but the "
            f"model/featurization expects K={K}")

    V_list, uvw = [], None
    for ms in sub:
        V_sr, uvw_ms = _read_vis_sr(ms, "DATA", B, n_times)
        V_list.append(V_sr)
        uvw = uvw_ms if uvw is None else uvw
    V = jnp.asarray(np.stack(V_list))                 # (Nf, T, B, 2, 2, 2)
    uu, vv, ww = (uvw.reshape(-1, 3)[:, i].astype(np.float32)
                  for i in range(3))
    Ccal = jnp.stack([
        coherency.predict_coherencies_sr(uu, vv, ww, cal.sky, float(f))
        for f in freqs])

    # Match the model scale to the (unit-RMS) data before solving.  The
    # catalog-flux sky predicts amplitudes ~1e3-1e4 against O(1) data; the
    # Jones solutions absorb the gain eventually, but the chi2-init L-BFGS
    # starting from J=I sees cost ~|C|^4 and its line-search dot products
    # overflow float32 long before convergence.  A single global factor
    # keeps relative fluxes (per-direction gain stays J's job) and rho
    # rides along because the analytic rho is flux-proportional.
    m_rms = float(jnp.sqrt(jnp.mean(jnp.sum(
        Ccal.sum(axis=1) ** 2, axis=-1))))
    v_rms = float(jnp.sqrt(jnp.mean(jnp.sum(V ** 2, axis=-1))))
    scale = v_rms / max(m_rms, 1e-12)
    Ccal = Ccal * scale
    rho = cal.rho * scale

    cfg = solver.SolverConfig(n_stations=N, n_dirs=K, n_poly=n_poly,
                              admm_iters=admm_iters,
                              lbfgs_iters=lbfgs_iters,
                              init_iters=init_iters, polytype=0)
    res = solver.solve_admm(V, Ccal, jnp.asarray(freqs, jnp.float32), f0,
                            jnp.asarray(rho), cfg, n_chunks=Ts)

    hadd = influence_mod.consensus_hadd_scalars(
        rho, np.full(K, 0.001, np.float32), freqs, f0, 0,
        n_poly=n_poly, polytype=0)
    Rk = solver.residual_to_kernel(res.residual[0])
    inf = influence_mod.influence_visibilities(Rk, Ccal[0], res.J[0], hadd,
                                               N, Ts, perdir=True)
    summary = influence_mod.perdir_summary(inf.vis, inf.llr, Ccal[0],
                                           res.J[0])
    return assemble_features(inf.vis, summary, uvw, freqs,
                             cal.separations, cal.azimuth, cal.elevation,
                             npix=Ninf)
