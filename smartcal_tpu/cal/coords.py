"""Spherical-astronomy coordinate transforms, vectorized in jnp.

Parity targets (reference): ``calibration/calibration_tools.py:6-84``
(radectolm, lmtoradec, radToRA, radToDec).  The reference operates on python
scalars with ``math``; here every function maps over arrays so a whole sky
model transforms in one fused XLA op.
"""

import jax.numpy as jnp
import numpy as np


def radectolm(ra, dec, ra0, dec0):
    """Direction cosines (l, m, n-1) of sources (ra, dec) about phase center
    (ra0, dec0).  Reference: calibration_tools.py:6-16.

    Returns (l, m, n) where n = sqrt(1-l^2-m^2) - 1 (the reference's
    convention: n is the *excess* path, so the phase term is u*l+v*m+w*n).
    """
    ra = jnp.asarray(ra)
    dec = jnp.asarray(dec)
    # reference quirk: if dec0 < 0 <= dec, wrap dec0 by 2pi (no-op for sin/cos
    # but kept for bit-parity of the branch in the scalar original)
    dec0 = jnp.where((dec0 < 0.0) & (dec >= 0.0), dec0 + 2.0 * jnp.pi, dec0)
    l = jnp.sin(ra - ra0) * jnp.cos(dec)
    m = -(jnp.cos(ra - ra0) * jnp.cos(dec) * jnp.sin(dec0)
          - jnp.cos(dec0) * jnp.sin(dec))
    n = jnp.sqrt(jnp.maximum(1.0 - l * l - m * m, 0.0)) - 1.0
    return l, m, n


def lmtoradec(l, m, ra0, dec0):
    """Inverse of radectolm (small-field approximation).
    Reference: calibration_tools.py:19-40."""
    l = jnp.asarray(l)
    m = jnp.asarray(m)
    sind0 = jnp.sin(dec0)
    cosd0 = jnp.cos(dec0)
    d0 = m ** 2 * sind0 ** 2 + l ** 2 - 2.0 * m * cosd0 * sind0
    sind = jnp.sqrt(jnp.abs(sind0 ** 2 - d0))
    cosd = jnp.sqrt(jnp.abs(cosd0 ** 2 + d0))
    sind = jnp.where(sind0 > 0, jnp.abs(sind), -jnp.abs(sind))
    dec = jnp.arctan2(sind, cosd)
    ra = jnp.where(
        l != 0.0,
        jnp.arctan2(-l, cosd0 - m * sind0),
        jnp.arctan2(1e-10, cosd0 - m * sind0)) + ra0
    return ra, dec


def rad_to_ra(rad):
    """Radians -> (hr, min, sec).  Reference: calibration_tools.py:43-61.
    Host-side helper (returns python floats)."""
    rad = float(rad)
    if rad < 0:
        rad += 2 * np.pi
    v = rad * 12.0 / np.pi
    hr = int(np.floor(v))
    v = (v - hr) * 60
    mins = int(np.floor(v))
    sec = (v - mins) * 60
    return hr % 24, mins % 60, sec


def rad_to_dec(rad):
    """Radians -> (deg, min, sec).  Reference: calibration_tools.py:64-84.

    Deviation from the reference: for declinations in (-1, 0) deg the
    reference's ``mult*(deg%180)`` loses the sign (deg==0); here the sign is
    carried by the first nonzero field so ``dms_to_rad`` round-trips."""
    rad = float(rad)
    mult = -1 if rad < 0 else 1
    v = abs(rad) * 180.0 / np.pi
    deg = int(np.floor(v))
    v = (v - deg) * 60
    mins = int(np.floor(v))
    sec = (v - mins) * 60
    deg, mins = deg % 180, mins % 60
    if mult < 0 and deg == 0:
        return 0, -mins, -sec if mins == 0 else sec
    return mult * deg, mins, sec


def hms_to_rad(h, m, s):
    """(hr, min, sec) -> radians (RA convention)."""
    return (h + m / 60.0 + s / 3600.0) * np.pi / 12.0


def dms_to_rad(d, m, s):
    """(deg, min, sec) -> radians (Dec convention).  Sign carried by the
    first nonzero field (see rad_to_dec for the |dec| < 1 deg case)."""
    neg = (np.signbit(d) or (d == 0 and (np.signbit(m)
                                         or (m == 0 and np.signbit(s)))))
    sign = -1.0 if neg else 1.0
    return sign * (abs(d) + abs(m) / 60.0 + abs(s) / 3600.0) * np.pi / 180.0


def angular_separation(ra1, dec1, ra2, dec2):
    """Great-circle separation (rad) via the haversine form (stable for
    small separations).  Replaces casacore ``measures.separation``
    (reference influence_tools.py:16-80) with pure math."""
    sdlat = jnp.sin(0.5 * (dec2 - dec1))
    sdlon = jnp.sin(0.5 * (ra2 - ra1))
    a = sdlat ** 2 + jnp.cos(dec1) * jnp.cos(dec2) * sdlon ** 2
    return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


def azel_from_radec(ra, dec, lst, lat):
    """Azimuth/elevation of (ra, dec) for local sidereal time ``lst`` and
    geodetic latitude ``lat`` (all radians).  Replaces the casacore AZEL
    measures conversion (reference influence_tools.py:83-159) with the
    standard hour-angle formulae."""
    ha = lst - ra
    sin_el = (jnp.sin(dec) * jnp.sin(lat)
              + jnp.cos(dec) * jnp.cos(lat) * jnp.cos(ha))
    el = jnp.arcsin(jnp.clip(sin_el, -1.0, 1.0))
    az = jnp.arctan2(
        -jnp.cos(dec) * jnp.sin(ha),
        jnp.sin(dec) * jnp.cos(lat) - jnp.cos(dec) * jnp.sin(lat) * jnp.cos(ha))
    az = jnp.where(az < 0, az + 2 * jnp.pi, az)
    return az, el
