"""Sky -> visibility coherency prediction (the in-framework replacement for
SAGECal's ``sagecal_gpu`` prediction step).

Parity targets: ``calibration/calibration_tools.py:215-464``
(skytocoherencies, skytocoherencies_torch, skytocoherencies_uvw).

Design: the reference loops over sources in python, each adding one DFT term
to its cluster's coherency.  Here the sky is a struct-of-arrays over sources
and the whole prediction is ONE einsum-shaped kernel:
    phase (S, T) -> flux-scaled complex exponentials -> segment-sum to (K, T).
Per-source work is a (S, T) outer product — large, batched, bf16-friendly —
exactly what the MXU wants; the python-level source loop is gone.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

C_LIGHT = 2.99792458e8


class SkyArrays:
    """Struct-of-arrays sky model (host-built, device-consumed).

    Fields (S sources):
      lmn       (S, 3) direction cosines (l, m, n-1) about the phase center
      flux_coef (S, 4) [log sI at f0, sp1, sp2, sp3] spectral log-polynomial
      f0        (S,)   reference frequency per source
      gauss     (S, 3) [major, minor, pa]; zeros for point sources
      is_gauss  (S,)   bool
      cluster   (S,)   cluster id in [0, K)
    """

    def __init__(self, lmn, flux_coef, f0, gauss, is_gauss, cluster, n_clusters):
        self.lmn = jnp.asarray(lmn, jnp.float32)
        self.flux_coef = jnp.asarray(flux_coef, jnp.float32)
        self.f0 = jnp.asarray(f0, jnp.float32)
        self.gauss = jnp.asarray(gauss, jnp.float32)
        self.is_gauss = jnp.asarray(is_gauss, bool)
        self.cluster = jnp.asarray(cluster, jnp.int32)
        self.n_clusters = int(n_clusters)


@partial(jax.jit, static_argnames=("n_clusters", "smear"))
def _predict(uvw_scaled, lmn, flux_coef, f0, gauss, is_gauss, cluster,
             n_clusters, freq, smear=False, fdelta_over_freq=0.0):
    """Core kernel.  uvw_scaled: (T, 3) already multiplied by 2*pi*f/c."""
    uu, vv, ww = uvw_scaled[:, 0], uvw_scaled[:, 1], uvw_scaled[:, 2]
    l, m, n = lmn[:, 0], lmn[:, 1], lmn[:, 2]

    # spectral power law: sI = exp(log sI0 + sp1*fr + sp2*fr^2 + sp3*fr^3)
    fr = jnp.log(freq / f0)                               # (S,)
    log_si = (flux_coef[:, 0] + flux_coef[:, 1] * fr
              + flux_coef[:, 2] * fr ** 2 + flux_coef[:, 3] * fr ** 3)
    si = jnp.exp(log_si)

    # (S, T) phase
    phase = l[:, None] * uu[None, :] + m[:, None] * vv[None, :] \
        + n[:, None] * ww[None, :]
    amp = si[:, None]

    if smear:
        # bandwidth smearing, numpy sinc normalization:
        # |sinc(phase * 0.5 * fdelta / pi)| with np.sinc(x) = sin(pi x)/(pi x)
        amp = amp * jnp.abs(jnp.sinc(phase * 0.5 * fdelta_over_freq / jnp.pi))

    # Gaussian envelope (reference skytocoherencies_uvw:434-452): project
    # uv onto the source plane, rotate by position angle, scale axes.
    # NOTE reference quirk kept for parity: acos() is applied to the n-EXCESS
    # (sqrt(1-l^2-m^2) - 1, near 0), not the true direction cosine (near 1),
    # so phi ~ -pi/2 near the phase center (calibration_tools.py:436).
    phi = -jnp.arccos(jnp.clip(n, -1.0, 1.0))
    xi = -jnp.arctan2(-l, m)
    cxi, sxi = jnp.cos(xi), jnp.sin(xi)
    cphi, sphi = jnp.cos(phi), jnp.sin(phi)
    eX = 2.0 * gauss[:, 0]
    eY = 2.0 * gauss[:, 1]
    cpa, spa = jnp.cos(gauss[:, 2]), jnp.sin(gauss[:, 2])
    uup = (cxi[:, None] * uu[None, :] - (cphi * sxi)[:, None] * vv[None, :]
           + (sphi * sxi)[:, None] * ww[None, :])
    vvp = (sxi[:, None] * uu[None, :] + (cphi * cxi)[:, None] * vv[None, :]
           - (sphi * cxi)[:, None] * ww[None, :])
    uut = eX[:, None] * (cpa[:, None] * uup - spa[:, None] * vvp)
    vvt = eY[:, None] * (spa[:, None] * uup + cpa[:, None] * vvp)
    envelope = 0.5 * jnp.pi * jnp.exp(-(uut * uut + vvt * vvt))
    amp = amp * jnp.where(is_gauss[:, None], envelope, 1.0)

    # split-real output (see cal/creal.py: no complex dtypes on device)
    xx = jnp.stack([amp * jnp.cos(phase), amp * jnp.sin(phase)], axis=-1)
    per_cluster = jax.ops.segment_sum(xx, cluster, num_segments=n_clusters)

    T = uvw_scaled.shape[0]
    C = jnp.zeros((n_clusters, T, 4, 2), dtype=jnp.float32)
    C = C.at[:, :, 0, :].set(per_cluster)
    C = C.at[:, :, 3, :].set(per_cluster)
    return C


def predict_coherencies_sr(uu, vv, ww, sky: SkyArrays, freq,
                           smear=False, fdelta=180e3):
    """Split-real coherencies C (K, T, 4, 2) for uvw (meters) at ``freq``.

    XX = YY = sum over cluster sources of sI(f) * exp(i(ul+vm+wn))
    [* smear * gaussian envelope]; XY = YX = 0.
    Reference: skytocoherencies_uvw, calibration_tools.py:371-464.
    This is the device API — chain it into the influence kernels
    (cal/kernels.py ``*_sr``) without host round-trips.
    """
    scale = 2.0 * np.pi * freq / C_LIGHT
    uvw = jnp.stack([jnp.asarray(uu), jnp.asarray(vv), jnp.asarray(ww)],
                    axis=-1).astype(jnp.float32) * np.float32(scale)
    return _predict(uvw, sky.lmn, sky.flux_coef, sky.f0, sky.gauss,
                    sky.is_gauss, sky.cluster, sky.n_clusters,
                    jnp.float32(freq), smear=smear,
                    fdelta_over_freq=float(fdelta / freq) if smear else 0.0)


@partial(jax.jit, static_argnames=("n_clusters", "smear"))
def _predict_multi(uvw_scaled, fofs, lmn, flux_coef, f0, gauss, is_gauss,
                   cluster, n_clusters, freqs, smear=False):
    """Batched core: uvw_scaled (Nf, T, 3) PRE-scaled by 2 pi f/c (scaled
    eagerly by the wrapper, outside this jit — an in-jit scale fuses into
    the phase accumulation as an fma and shifts the f32-wrapped DFT
    phases off the single-band path's values); fofs (Nf,) = fdelta/f
    (zeros when not smearing)."""
    def one(us, f, fof):
        return _predict(us, lmn, flux_coef, f0, gauss, is_gauss,
                        cluster, n_clusters, f, smear=smear,
                        fdelta_over_freq=fof)

    return jax.vmap(one)(uvw_scaled, freqs, fofs)


def predict_coherencies_multi_sr(uu, vv, ww, sky: SkyArrays, freqs,
                                 smear=False, fdelta=180e3):
    """Split-real coherencies for ALL sub-bands: (Nf, K, T, 4, 2) in ONE
    device dispatch (the vmapped form of :func:`predict_coherencies_sr`,
    removing the envs' per-frequency python loop).

    Numerically matched to stacking the single-band calls: the per-band
    uvw scale factors are computed on host with the SAME f32 scalar
    arithmetic as the single-band wrapper (NEP-50: python floats are
    weak against the f32 channel frequencies), so the (huge,
    f32-wrapped) DFT phases agree with the loop path's.
    """
    freqs32 = np.asarray(freqs, np.float32)
    scales = jnp.asarray(2.0 * np.pi * freqs32 / C_LIGHT, jnp.float32)
    fofs = jnp.asarray(fdelta / np.asarray(freqs, np.float64) if smear
                       else np.zeros_like(freqs32), jnp.float32)
    uvw = jnp.stack([jnp.asarray(uu), jnp.asarray(vv), jnp.asarray(ww)],
                    axis=-1).astype(jnp.float32)
    uvw_scaled = uvw[None, :, :] * scales[:, None, None]   # eager, like
    return _predict_multi(uvw_scaled, fofs, sky.lmn,       # the 1-band path
                          sky.flux_coef, sky.f0, sky.gauss, sky.is_gauss,
                          sky.cluster, sky.n_clusters,
                          jnp.asarray(freqs, jnp.float32), smear=smear)


def predict_coherencies(uu, vv, ww, sky: SkyArrays, freq,
                        smear=False, fdelta=180e3):
    """Complex host-edge wrapper: returns C (K, T, 4) complex64."""
    C = predict_coherencies_sr(uu, vv, ww, sky, freq, smear=smear,
                               fdelta=fdelta)
    C = np.asarray(C)
    return (C[..., 0] + 1j * C[..., 1]).astype(np.complex64)
