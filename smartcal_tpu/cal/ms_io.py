"""Measurement-Set data edge: host-side I/O behind one small API.

The reference talks to CASA Measurement Sets through python-casacore
(``calibration/casa_io.py:9-72`` read_corr/write_corr, ``generate_data.py:
877-887`` add_column, ``changefreq.py`` SPECTRAL_WINDOW rewrite,
``addnoise.py`` AWGN at a given SNR) and averages real observations with an
external DP3 run (``generate_data.py:623-681`` extract_dataset).  None of
that is TPU work — it is the host-side data edge — so here it lives in one
numpy module with two storage backends:

* **casacore**, used when python-casacore is importable and the path is a
  real MS (``table.dat`` present).  Import is gated: nothing in the package
  requires casacore to exist.
* **sct**, the framework's own native columnar store (``TABLE.sct``, one
  binary file written/read by the first-party C++ library in
  :mod:`smartcal_tpu.native` — the in-build counterpart of the casacore
  table system).  Default write format when the native library is
  available; ``SMARTCAL_MS_FORMAT=npz`` forces the pure-python backend.
* **npz**, an MS-shaped directory (``MAIN.npz`` + ``META.npz``), the
  no-toolchain fallback with identical semantics.

Both synthetic backends share the real-MS row semantics: one row per
(time, antenna pair) INCLUDING autocorrelations, sorted by
TIME,ANTENNA1,ANTENNA2, DATA of shape (nrows, nchan, 4).  They are the
synthetic stand-in the rest of the pipeline (featurization, evaluate CLI)
exercises in tests, through the very same code path a real MS would take.

Everything here is host-side numpy; device work happens downstream on the
split-real arrays these functions return.
"""

from __future__ import annotations

import os
from typing import List, NamedTuple, Optional

import numpy as np

try:  # gated: real MS support only when python-casacore is installed
    from casacore import tables as _ctab
except Exception:  # pragma: no cover - exercised implicitly everywhere
    _ctab = None

MAIN = "MAIN.npz"
META = "META.npz"
SCT = "TABLE.sct"

# Columns every store carries; extra data columns (MODEL_DATA, ...) are
# created on demand by add_column.
_BASE_COLS = ("TIME", "ANTENNA1", "ANTENNA2", "UVW", "INTERVAL", "DATA")


def is_sct_ms(path) -> bool:
    return os.path.isfile(os.path.join(path, SCT))


def is_npz_ms(path) -> bool:
    """True for any synthetic (non-casacore) store, either backend."""
    return (os.path.isfile(os.path.join(path, MAIN)) or is_sct_ms(path))


def _is_casa_ms(path) -> bool:
    return os.path.isfile(os.path.join(path, "table.dat"))


def _write_format() -> str:
    fmt = os.environ.get("SMARTCAL_MS_FORMAT", "").strip().lower()
    if fmt in ("sct", "npz"):
        return fmt
    if fmt:
        raise ValueError(
            f"SMARTCAL_MS_FORMAT={fmt!r}: expected 'sct' or 'npz'")
    from smartcal_tpu import native
    return "sct" if native.available() else "npz"


def _load(path):
    if is_sct_ms(path):
        from smartcal_tpu import native
        cols = native.sct_read(os.path.join(path, SCT))
        main = {k[5:]: v for k, v in cols.items() if k.startswith("MAIN/")}
        meta = {k[5:]: v for k, v in cols.items() if k.startswith("META/")}
        return main, meta
    if not os.path.isfile(os.path.join(path, MAIN)):
        raise FileNotFoundError(f"not a synthetic MS (sct or npz): {path}")
    with np.load(os.path.join(path, MAIN)) as z:
        main = dict(z)
    with np.load(os.path.join(path, META)) as z:
        meta = dict(z)
    return main, meta


def _store(path, main, meta):
    os.makedirs(path, exist_ok=True)
    fmt = _write_format()
    # Crash-window discipline per branch (_load PREFERS sct):
    #  * writing sct over an npz store: write first, remove after — a
    #    failed/interrupted sct_write must not destroy the npz original,
    #    and once TABLE.sct lands readers already see the new data.
    #  * writing npz over an sct store: remove TABLE.sct FIRST — with it
    #    present, a crash after savez would leave readers silently serving
    #    the stale pre-mutation sct forever; remove-first turns that
    #    window into a loud missing-store error instead.
    if fmt == "sct":
        from smartcal_tpu import native
        cols = {"MAIN/" + k: v for k, v in main.items()}
        cols.update({"META/" + k: v for k, v in meta.items()})
        native.sct_write(os.path.join(path, SCT), cols)
        stale = (MAIN, META)
    else:
        f = os.path.join(path, SCT)
        if os.path.isfile(f):
            os.remove(f)
        np.savez(os.path.join(path, MAIN), **main)
        np.savez(os.path.join(path, META), **meta)
        stale = ()
    for name in stale:
        f = os.path.join(path, name)
        if os.path.isfile(f):
            os.remove(f)


class MSInfo(NamedTuple):
    """Shape/metadata summary (the subtable reads of
    generate_data.py:727-746)."""

    n_stations: int
    n_baselines: int
    n_times: int
    n_chan: int
    freqs: np.ndarray      # (nchan,) CHAN_FREQ
    ref_freq: float
    ra0: float
    dec0: float
    t0: float              # first TIME value (s)
    interval: float        # integration time (s)


def ms_info(path) -> MSInfo:
    if _ctab is not None and _is_casa_ms(path):
        return _casa_ms_info(path)
    main, meta = _load(path)
    n_st = int(meta["N_ANTENNA"])
    b = n_st * (n_st - 1) // 2
    nrows, nchan, _ = main["DATA"].shape
    # rows per integration: B + N with autocorrelation rows (what
    # write_observation_ms emits), plain B without (extract_dataset
    # preserves whatever structure the source casacore MS had) — count
    # the actual autocorrelation rows instead of assuming
    n_auto = int(np.count_nonzero(main["ANTENNA1"] == main["ANTENNA2"]))
    rows_per_time = b + n_st if n_auto else b
    return MSInfo(
        n_stations=n_st, n_baselines=b, n_times=nrows // rows_per_time,
        n_chan=nchan, freqs=np.asarray(meta["CHAN_FREQ"], np.float64),
        ref_freq=float(meta["REF_FREQUENCY"]), ra0=float(meta["RA0"]),
        dec0=float(meta["DEC0"]), t0=float(main["TIME"][0]),
        interval=float(main["INTERVAL"][0]))


def read_corr(path, colname: str = "MODEL_DATA"):
    """MS column -> (uu, vv, ww, xx, xy, yx, yy), autocorrelations excluded.

    Row order: TIME major, then baseline p<q — the reference's sorted query
    (casa_io.py:9-43).  Channel 0 only, like the reference.
    """
    if _ctab is not None and _is_casa_ms(path):
        return _casa_read_corr(path, colname)
    main, _ = _load(path)
    if colname not in main:
        raise KeyError(f"column {colname} not in {path}")
    cross = main["ANTENNA1"] != main["ANTENNA2"]
    vl = main[colname][cross, 0]                      # (B*T, 4) complex
    uvw = main["UVW"][cross]
    return (uvw[:, 0].astype(np.float32), uvw[:, 1].astype(np.float32),
            uvw[:, 2].astype(np.float32), vl[:, 0].astype(np.csingle),
            vl[:, 1].astype(np.csingle), vl[:, 2].astype(np.csingle),
            vl[:, 3].astype(np.csingle))


def write_corr(path, xx, xy, yx, yy, colname: str = "CORRECTED_DATA"):
    """Write correlations into ``colname`` (cross rows, all channels get the
    channel-0 value — casa_io.py:46-72)."""
    if _ctab is not None and _is_casa_ms(path):
        return _casa_write_corr(path, xx, xy, yx, yy, colname)
    main, meta = _load(path)
    if colname not in main:
        add_column(path, colname)
        main, meta = _load(path)
    cross = main["ANTENNA1"] != main["ANTENNA2"]
    vl = main[colname]
    block = np.stack([xx, xy, yx, yy], axis=-1).astype(vl.dtype)
    vl[cross] = block[:, None, :]                    # broadcast over chans
    main[colname] = vl
    _store(path, main, meta)


def add_column(path, colname: str):
    """Add a DATA-shaped complex column, zero-filled
    (generate_data.py:877-887)."""
    if _ctab is not None and _is_casa_ms(path):
        return _casa_add_column(path, colname)
    main, meta = _load(path)
    if colname not in main:
        main[colname] = np.zeros_like(main["DATA"])
        _store(path, main, meta)


def change_freq(path, freq: float):
    """Rewrite SPECTRAL_WINDOW to a single frequency (changefreq.py role)."""
    if _ctab is not None and _is_casa_ms(path):
        return _casa_change_freq(path, freq)
    main, meta = _load(path)
    nchan = main["DATA"].shape[1]
    meta["CHAN_FREQ"] = np.full(nchan, freq, np.float64)
    meta["REF_FREQUENCY"] = np.float64(freq)
    _store(path, main, meta)


def add_noise(path, snr: float, rng=None, colname: str = "DATA"):
    """AWGN at the given SNR into ``colname`` (addnoise.py role):
    noise_std = ||data|| / (snr * sqrt(2 * size))."""
    rng = rng or np.random.default_rng(0)
    main, meta = _load(path)
    d = main[colname]
    scale = np.linalg.norm(d) / (snr * np.sqrt(2.0 * d.size))
    noise = (rng.standard_normal(d.shape)
             + 1j * rng.standard_normal(d.shape)) * scale
    main[colname] = (d + noise).astype(d.dtype)
    _store(path, main, meta)


# ---------------------------------------------------------------------------
# Synthetic writer: Observation + split-real V -> MS-shaped store
# ---------------------------------------------------------------------------

def write_observation_ms(path, obs, V_sr, freq: float,
                         extra_cols: Optional[List[str]] = None):
    """Write ONE sub-band of a simulated observation as an MS-shaped store.

    obs : cal.observation.Observation (uvw (T,B,3), times, ra0/dec0)
    V_sr: (T, B, 2, 2, 2) split-real visibilities for this sub-band
    freq: channel frequency (Hz)

    Emits real-MS row structure: (B + N) rows per time (autocorrelations
    zero), sorted TIME,ANTENNA1,ANTENNA2 — so readers cannot tell this from
    a casacore-exported single-channel MS.
    """
    from smartcal_tpu.cal import creal

    n_st = obs.n_stations
    T, B = V_sr.shape[0], V_sr.shape[1]
    assert B == n_st * (n_st - 1) // 2
    p, q = np.triu_indices(n_st, 0)                  # incl. autocorr, sorted
    npair = p.size                                   # B + N
    cross = p != q

    Vc = creal.fuse(np.asarray(V_sr)).reshape(T, B, 4)   # (T, B, 4) complex
    data = np.zeros((T * npair, 1, 4), np.csingle)
    data[np.tile(cross, T).nonzero()[0], 0, :] = Vc.reshape(T * B, 4)

    uvw_rows = np.zeros((T * npair, 3), np.float32)
    uvw_rows[np.tile(cross, T).nonzero()[0]] = \
        np.asarray(obs.uvw, np.float32).reshape(T * B, 3)

    times = np.asarray(obs.times, np.float64)
    t_int = float(times[1] - times[0]) if T > 1 else 1.0
    # absolute epoch seconds consistent with lst0 = OMEGA * t0 mod 2pi
    from smartcal_tpu.cal.observation import OMEGA_EARTH
    t0_abs = obs.lst0 / OMEGA_EARTH
    main = {
        "TIME": np.repeat(t0_abs + times, npair),
        "ANTENNA1": np.tile(p, T).astype(np.int32),
        "ANTENNA2": np.tile(q, T).astype(np.int32),
        "UVW": uvw_rows,
        "INTERVAL": np.full(T * npair, t_int, np.float64),
        "DATA": data,
    }
    for c in (extra_cols or []):
        main[c] = np.zeros_like(data)
    meta = {
        "CHAN_FREQ": np.asarray([freq], np.float64),
        "REF_FREQUENCY": np.float64(freq),
        "RA0": np.float64(obs.ra0), "DEC0": np.float64(obs.dec0),
        "N_ANTENNA": np.int64(n_st),
    }
    _store(path, main, meta)
    return path


def observation_to_ms_set(outdir, obs, V_all_sr, basename="L_SB"):
    """One MS per sub-band (the LOFAR L_SB*.MS convention,
    dosimul.sh:14-32).  V_all_sr: (Nf, T, B, 2, 2, 2)."""
    freqs = np.asarray(obs.freqs, np.float64)
    paths = []
    for fi in range(V_all_sr.shape[0]):
        ms = os.path.join(outdir, f"{basename}{fi}.MS")
        write_observation_ms(ms, obs, np.asarray(V_all_sr[fi]),
                             float(freqs[fi]))
        paths.append(ms)
    return paths


# ---------------------------------------------------------------------------
# extract_dataset: DP3-averaging replacement (generate_data.py:623-681)
# ---------------------------------------------------------------------------

def _load_any(path):
    """(main, meta) column dicts from any backend — sct/npz directly, or a
    casacore MS read column-by-column into the same layout (so the
    averaging/extraction logic below is backend-agnostic; extracted work
    files are always written as synthetic stores, leaving real MSs
    untouched)."""
    if is_npz_ms(path):
        return _load(path)
    if _ctab is None or not _is_casa_ms(path):  # pragma: no cover
        raise FileNotFoundError(f"not an MS (npz or casacore): {path}")
    # pragma: no cover - needs casacore
    tt = _ctab.table(path, readonly=True)
    t1 = tt.query(sortlist="TIME,ANTENNA1,ANTENNA2")
    main = {c: t1.getcol(c) for c in _BASE_COLS if c in t1.colnames()}
    n_st = int(max(main["ANTENNA1"].max(), main["ANTENNA2"].max())) + 1
    t1.close()
    tt.close()
    info = _casa_ms_info(path)
    meta = {"CHAN_FREQ": info.freqs,
            "REF_FREQUENCY": np.float64(info.ref_freq),
            "RA0": np.float64(info.ra0), "DEC0": np.float64(info.dec0),
            "N_ANTENNA": np.int64(n_st)}
    return main, meta


def _peek_freq(path) -> float:
    """First channel frequency without loading the main data columns."""
    if is_sct_ms(path):
        from smartcal_tpu import native
        freq = native.sct_read_one(os.path.join(path, SCT),
                                   "META/CHAN_FREQ")
        return float(np.asarray(freq).ravel()[0])
    if is_npz_ms(path):
        with np.load(os.path.join(path, META)) as z:
            return float(np.asarray(z["CHAN_FREQ"]).ravel()[0])
    if _ctab is not None and _is_casa_ms(path):  # pragma: no cover
        tf = _ctab.table(os.path.join(path, "SPECTRAL_WINDOW"),
                         readonly=True)
        f = float(tf.getcol("CHAN_FREQ")[0][0])
        tf.close()
        return f
    raise FileNotFoundError(f"not an MS (npz or casacore): {path}")


def extract_dataset(mslist: List[str], timesec: float, Nf: int = 3,
                    rng=None, outdir: str = ".", basename: str = "EX_SB"):
    """Choose ``Nf`` sub-band MSs, average their channels to one, and cut a
    random ``timesec``-second time window; write the results as NEW npz
    stores (work files — sources are only read).

    Sub-band choice matches the reference: always the lowest and highest
    frequency plus Nf-2 random interior ones (:662-668).  The averaging the
    reference delegates to DP3 (avg.freqstep=64, :648-658) is a mean over
    the channel axis here.
    """
    rng = rng or np.random.default_rng(0)
    # sort by actual sub-band frequency, not name (lexicographic order
    # breaks for unpadded L_SB10.MS vs L_SB2.MS, silently mispicking the
    # endpoint sub-bands below)
    mslist = sorted(mslist, key=_peek_freq)
    if len(mslist) < Nf:
        raise ValueError(f"need >= {Nf} MS, got {len(mslist)}")

    main0, _ = _load_any(mslist[0])
    tcol = main0["TIME"]
    tstart, tend = float(tcol[0]), float(tcol[-1])
    t_lo = rng.random() * max(tend - tstart - timesec, 0.0) + tstart
    t_hi = t_lo + timesec

    if len(mslist) == Nf:
        sub = list(mslist)
    else:
        interior = np.sort(rng.choice(np.arange(1, len(mslist) - 1),
                                      Nf - 2, replace=False))
        sub = [mslist[0]] + [mslist[i] for i in interior] + [mslist[-1]]

    out = []
    for ci, src in enumerate(sub):
        dst = os.path.join(outdir, f"{basename}{ci}.MS")
        if os.path.abspath(dst) in {os.path.abspath(m) for m in mslist}:
            raise ValueError(
                f"extract_dataset output {dst} would overwrite a source MS;"
                " use a different outdir/basename")
        main, meta = _load_any(src)
        sel = (main["TIME"] >= t_lo) & (main["TIME"] <= t_hi)
        if not np.any(sel):
            raise ValueError(
                f"extract_dataset: the {timesec}s window [{t_lo:.1f}, "
                f"{t_hi:.1f}] selects no rows of {src} (integration "
                "interval longer than the window?) — increase timesec")
        new_main = {}
        for k, v in main.items():
            v = v[sel]
            if v.ndim == 3:                       # data columns: chan mean
                v = v.mean(axis=1, keepdims=True)
            new_main[k] = v
        meta = dict(meta)
        meta["CHAN_FREQ"] = np.asarray(
            [float(np.mean(meta["CHAN_FREQ"]))], np.float64)
        _store(dst, new_main, meta)
        out.append(dst)
    return out


# ---------------------------------------------------------------------------
# casacore backend (thin; only reached when python-casacore is installed)
# ---------------------------------------------------------------------------

def _casa_ms_info(path) -> MSInfo:  # pragma: no cover - needs casacore
    tt = _ctab.table(path, readonly=True)
    a1 = tt.getcol("ANTENNA1")
    a2 = tt.getcol("ANTENNA2")
    n_st = int(max(a1.max(), a2.max())) + 1
    b = n_st * (n_st - 1) // 2
    t0 = float(tt[0]["TIME"])
    interval = float(tt[0]["INTERVAL"])
    nrows = tt.nrows()
    # count autocorrelation rows rather than guessing from divisibility
    # (T*(N-1) divisible by N+1 happens for real shapes, e.g. N=15, T=16)
    n_auto = int(np.count_nonzero(a1 == a2))
    rows_per_time = b + n_st if n_auto else b
    tt.close()
    tf = _ctab.table(os.path.join(path, "SPECTRAL_WINDOW"), readonly=True)
    freqs = np.asarray(tf.getcol("CHAN_FREQ")[0], np.float64)
    ref = float(tf.getcol("REF_FREQUENCY")[0])
    tf.close()
    fld = _ctab.table(os.path.join(path, "FIELD"), readonly=True)
    ra0, dec0 = (float(x) for x in fld.getcol("PHASE_DIR")[0][0])
    fld.close()
    return MSInfo(n_st, b, nrows // rows_per_time, freqs.size, freqs, ref,
                  ra0, dec0, t0, interval)


def _casa_read_corr(path, colname):  # pragma: no cover - needs casacore
    tt = _ctab.table(path, readonly=True)
    t1 = tt.query(sortlist="TIME,ANTENNA1,ANTENNA2",
                  columns="ANTENNA1,ANTENNA2,UVW," + colname)
    vl = t1.getcol(colname)
    a1, a2 = t1.getcol("ANTENNA1"), t1.getcol("ANTENNA2")
    uvw = t1.getcol("UVW")
    t1.close()
    tt.close()
    cross = a1 != a2
    return (uvw[cross, 0].astype(np.float32),
            uvw[cross, 1].astype(np.float32),
            uvw[cross, 2].astype(np.float32),
            vl[cross, 0, 0].astype(np.csingle),
            vl[cross, 0, 1].astype(np.csingle),
            vl[cross, 0, 2].astype(np.csingle),
            vl[cross, 0, 3].astype(np.csingle))


def _casa_write_corr(path, xx, xy, yx, yy, colname):  # pragma: no cover
    tt = _ctab.table(path, readonly=False)
    t1 = tt.query(sortlist="TIME,ANTENNA1,ANTENNA2",
                  columns="ANTENNA1,ANTENNA2," + colname)
    vl = t1.getcol(colname)
    cross = t1.getcol("ANTENNA1") != t1.getcol("ANTENNA2")
    block = np.stack([xx, xy, yx, yy], axis=-1)
    vl[cross] = block[:, None, :]
    t1.putcol(colname, vl)
    t1.close()
    tt.close()


def _casa_add_column(path, colname):  # pragma: no cover - needs casacore
    tt = _ctab.table(path, readonly=False)
    if colname not in tt.colnames():
        cd = tt.getcoldesc("DATA")
        cd["name"] = colname
        tt.addcols(_ctab.makecoldesc(colname, cd))
    tt.close()


def _casa_change_freq(path, freq):  # pragma: no cover - needs casacore
    tf = _ctab.table(os.path.join(path, "SPECTRAL_WINDOW"), readonly=False)
    ch = tf.getcol("CHAN_FREQ")
    ch[:] = freq
    tf.putcol("CHAN_FREQ", ch)
    tf.putcol("REF_FREQUENCY", np.full_like(tf.getcol("REF_FREQUENCY"),
                                            freq))
    tf.close()
