"""Residual Hessians, solution/residual derivatives, and the LLR detector.

Parity targets (reference ``calibration/calibration_tools.py``):
  * Hessianres / Hessianres_torch        :590-676   -> hessian_res
  * Dsolutions_r / Dsolutions_r_torch    :778-875   -> dsolutions_all
  * Dsolutions / Dsolutions_torch        :680-775   -> dsolutions (one r)
  * Dresiduals_r / Dresiduals_r_torch    :1028-1126 -> dresiduals_all
  * Dresiduals_rk                        :1129-1176 -> dresiduals_all_perdir
  * log_likelihood_ratio                 :1181-1223 -> log_likelihood_ratio

Shapes follow the reference conventions exactly so the influence engine and
golden tests line up 1:1:
  N stations, B = N(N-1)/2 baselines, T timeslots, K directions.
  R : (2*B*T, 2) complex residuals; sample ck's 2x2 block is R[2ck:2ck+2].
  C : (K, B*T, 4) coherencies; the 2x2 is C[k,ck].reshape(2,2,order='F').
  J : (K, 2N, 2) Jones solutions; station p's 2x2 is J[k, 2p:2p+2].
  Samples are time-major: ck = t*B + b, with baseline b enumerating p<q
  row-major (p ascending, q ascending within p).

TPU-first design decisions:
  1. All device math is SPLIT-REAL (see cal/creal.py): complex tensors are
     float32 (..., 2) planes.  The axon TPU backend's complex lowering is
     intermittently UNIMPLEMENTED (observed on hardware 2026-07-29), and
     split-real is the layout XLA maps onto the MXU anyway.  The ``*_sr``
     functions are the device API (chainable without host round-trips); the
     plain-named wrappers take/return numpy complex at the host edge.
  2. The reference's python triple loops over (k, t, p<q) become per-sample
     4x4 blocks computed as batched einsums + scatter-adds over the baseline
     axis.
  3. Where the math is linear in C (Dsolutions/Dresiduals), the time axis is
     summed BEFORE the kron expansion — an O(T) reduction in kron work the
     reference does not exploit.
  4. The per-direction 4N x 4N solves are batched with vmap; all 8
     perturbation directions r share one factorization per direction k.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from smartcal_tpu.cal import creal
from smartcal_tpu.cal import precision as _precision

EPS_SINGULAR = 1e-12   # reference: EPS in Dsolutions (calibration_tools.py:696)
EPS_DIV = 1e-12        # reference: EPS in log_likelihood_ratio (:1203)


def baseline_indices(n_stations):
    """(p, q) station indices per baseline, reference loop order
    ``for p in range(N-1): for q in range(p+1, N)``."""
    p, q = np.triu_indices(n_stations, 1)
    return jnp.asarray(p), jnp.asarray(q)


def baseline_onehots(n_stations, dtype=_precision.F32):
    """One-hot (N, B) selection matrices for the p and q station of each
    baseline — the scatter-free station<->baseline expansion shared by the
    solver's inner evaluation (cal/solver._cost_fn_onehot) and the
    optimized influence kernels below.  A gather ``J4[:, p_idx]`` becomes
    a matmul whose autodiff transpose is another matmul, and the forward
    segment-sum onto stations becomes ``onehot @ X`` with full lanes
    instead of a scatter-add.

    Built with NUMPY on host (constants under jit either way): shape-only
    helpers (solver.cost_eval_flops) call this outside any jit, and an
    eager ``jnp.eye`` there would execute on the default backend — which
    can be a wedged TPU tunnel when the helper is meant to stay
    CPU-side."""
    p_idx, q_idx = np.triu_indices(n_stations, 1)
    eye = np.eye(n_stations, dtype=np.dtype(dtype))
    return eye[:, p_idx], eye[:, q_idx]          # each (N, B)


def offdiag_index_map(n_stations):
    """(N, N) int32 map [p, q] -> baseline index b for p < q, else B (a
    zero-pad sentinel slot).  Each off-diagonal station block of the
    residual Hessian receives exactly ONE baseline's contribution, so the
    oracle's scatter-add placement is a pure permutation — reproduced
    bit-exactly by a static gather of the zero-padded block table, with
    no scatter lowering.  Host-side numpy: a compile-time constant."""
    p_idx, q_idx = np.triu_indices(n_stations, 1)
    B = p_idx.size
    m = np.full((n_stations, n_stations), B, np.int32)
    m[p_idx, q_idx] = np.arange(B)
    return m


def _split_samples_sr(Rs, Cs, n_stations):
    """Split-real (2BT, 2, 2) / (K, BT, 4, 2) -> time/baseline block form."""
    B = n_stations * (n_stations - 1) // 2
    K = Cs.shape[0]
    T = Cs.shape[1] // B
    R3 = Rs.reshape(T, B, 2, 2, 2)
    # order='F' 2x2: swap the matrix axes (pair axis stays last)
    C5 = jnp.swapaxes(Cs.reshape(K, T, B, 2, 2, 2), -3, -2)
    return R3, C5, B, T, K


def _jones_blocks_sr(Js, n_stations):
    """(K, 2N, 2, 2) -> (K, N, 2, 2, 2) with [k, p] = J[k, 2p:2p+2]."""
    K = Js.shape[0]
    return Js.reshape(K, n_stations, 2, 2, 2)


# ---------------------------------------------------------------------------
# Hessian of the residual
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("n_stations",))
def hessian_res_sr(Rs, Cs, Js, n_stations):
    """Residual Hessian H (K, 4N, 4N, 2), averaged over baselines*time.

    Per baseline (p, q) the contribution is
      off-diag  (p,q): -conj(C) (x) Res          (and its hermitian at (q,p))
      diag      (p,p): ((C Jq^H)(C Jq^H)^H)^T (x) I2
      diag      (q,q): ((Jp C)^H (Jp C))^T (x) I2
    Reference: Hessianres, calibration_tools.py:590-631.
    """
    R3, C5, B, T, K = _split_samples_sr(Rs, Cs, n_stations)
    J4 = _jones_blocks_sr(Js, n_stations)
    p_idx, q_idx = baseline_indices(n_stations)
    Jp = J4[:, p_idx]                      # (K, B, 2, 2, 2)
    Jq = J4[:, q_idx]

    # off-diagonal: sum_t kron(-conj(Ci), Res) -> (K, B, 4, 4, 2)
    off = -creal.einsum("ktbij,tbuv->kbiujv", creal.conj(C5), R3)
    off = off.reshape(K, B, 4, 4, 2)

    # diag at p: A1 = Ci Jq^H ; S = sum_t A1 A1^H
    A1 = creal.einsum("ktbuv,kbwv->ktbuw", C5, creal.conj(Jq))
    Sp = creal.einsum("ktbuw,ktbvw->kbuv", A1, creal.conj(A1))
    # diag at q: A2 = Jp Ci ; S = sum_t A2^H A2
    A2 = creal.einsum("kbuv,ktbvw->ktbuw", Jp, C5)
    Sq = creal.einsum("ktbuv,ktbuw->kbvw", creal.conj(A2), A2)

    # segment-sum baseline contributions onto stations
    Dp = jax.ops.segment_sum(jnp.swapaxes(Sp, 0, 1), p_idx,
                             num_segments=n_stations)    # (N, K, 2, 2, 2)
    Dq = jax.ops.segment_sum(jnp.swapaxes(Sq, 0, 1), q_idx,
                             num_segments=n_stations)
    Dsum = Dp + Dq
    # kron(S.T, I2)[2i+u, 2j+v] = S[j, i] * delta_uv  (I2 is real)
    eye2 = jnp.eye(2, dtype=Rs.dtype)
    diag_blocks = jnp.einsum("nkjiz,uv->nkiujvz", Dsum, eye2).reshape(
        n_stations, K, 4, 4, 2)

    H = jnp.zeros((K, n_stations, 4, n_stations, 4, 2), dtype=Rs.dtype)
    off_t = jnp.swapaxes(off, 0, 1)                      # (B, K, 4, 4, 2)
    H = H.at[:, p_idx, :, q_idx, :, :].add(off_t)
    herm = creal.conj(jnp.swapaxes(off_t, -3, -2))
    H = H.at[:, q_idx, :, p_idx, :, :].add(herm)
    sidx = jnp.arange(n_stations)
    H = H.at[:, sidx, :, sidx, :, :].add(diag_blocks)
    N4 = 4 * n_stations
    return H.reshape(K, N4, N4, 2) / (B * T)


def hessian_res(R, C, J, n_stations):
    """Complex host-edge wrapper (reference Hessianres signature)."""
    H = hessian_res_sr(creal.split(R), creal.split(C), creal.split(J),
                       n_stations)
    return creal.fuse(np.asarray(H))


def _hessian_res_core_sr(R3, C5, Jp, Jq, n_stations):
    """Scatter-free residual-Hessian core on PRE-SPLIT operands.

    Same math as :func:`hessian_res_sr` (the retained oracle) with the
    two scatter lowerings replaced by the solver's formulation moves:

      * the station segment-sums of the diagonal blocks become one-hot
        matmuls (``baseline_onehots`` — full lanes, and the transpose is
        a matmul rather than the scatter a ``segment_sum`` lowers to);
      * the off-diagonal block placement — a pure permutation, one
        baseline per (p, q) slot — becomes a static GATHER of the
        zero-padded block table (``offdiag_index_map``), bit-identical to
        the oracle's scatter-add.

    Taking ``R3/C5/Jp/Jq`` directly lets the influence engine hoist the
    split-real rebuilds out of its chunk loop (they are recomputed per
    chunk per kernel in the oracle chain).

    ONE copy of the math: this is ``_hessian_block_sums`` over the full
    baseline set followed by the shared ``_hessian_assemble`` placement
    tail — the same pieces the blocked (lax.scan) and baseline-sharded
    paths run per subset, so a formula fix lands in every path at once.
    """
    K, T, B = C5.shape[0], C5.shape[1], C5.shape[2]
    p_idx, q_idx = baseline_indices(n_stations)
    off, Dsum = _hessian_block_sums(R3, C5, Jp, Jq, p_idx, q_idx,
                                    n_stations)
    return _hessian_assemble(off, Dsum, n_stations, B, T)


@partial(jax.jit, static_argnames=("n_stations",))
def hessian_res_opt_sr(Rs, Cs, Js, n_stations):
    """Scatter-free :func:`hessian_res_sr` (the production influence-path
    kernel; the scatter-based original is retained as the parity oracle).
    Same signature, equal to float round-off (the one-hot matmul reorders
    the diagonal segment reductions)."""
    R3, C5, B, T, K = _split_samples_sr(Rs, Cs, n_stations)
    J4 = _jones_blocks_sr(Js, n_stations)
    p_idx, q_idx = baseline_indices(n_stations)
    return _hessian_res_core_sr(R3, C5, J4[:, p_idx], J4[:, q_idx],
                                n_stations)


# ---------------------------------------------------------------------------
# Blocked / baseline-sharded Hessian (the B ~ N^2 memory tier)
# ---------------------------------------------------------------------------
#
# At N >= 256 stations (B = 32640 baselines) the unblocked Hessian core's
# per-chunk einsum temporaries — A1/A2/Sp/Sq and their conjugates, each
# (K, Td, B, 2, 2, 2) — dominate peak memory (the inputs themselves are a
# fraction of the live set).  The pieces below compute the SAME math from
# an arbitrary SUBSET of baselines (a scan block, or a mesh shard's local
# slice), so the temporaries scale with the block/shard size while the
# output stays the full (K, 4N, 4N, 2) per-direction Hessian:
#
# * ``_hessian_block_sums``   — per-subset off-diagonal blocks + one-hot
#   station sums (one-hots built by equality against the subset's OWN
#   p/q indices, so zero-padding/sentinel indices contribute nothing);
# * ``_hessian_assemble``     — the placement tail of the core (padded
#   gather of the off-diagonal table + diag kron), shared verbatim;
# * ``hessian_res_core_blocked_sr`` — lax.scan over baseline blocks on
#   the hoisted per-chunk operands (the blocked twin of
#   ``_hessian_res_core_sr``, selected by the influence engine's static
#   ``block_baselines``);
# * shard callers (cal/influence._chunk_influence_bshard) place a local
#   subset at its global offset and psum the assembled partial — the ONE
#   collective of the baseline-sharded Hessian.


def _block_onehot(idx, n_stations, dtype):
    """(N, nb) one-hot from a station-index vector (device-built, traced
    indices allowed — shard-local p/q slices are operands, not
    constants).  Sentinel indices >= N (zero-pad slots) produce all-zero
    columns, so padded baselines contribute nothing."""
    return (idx[None, :] == jnp.arange(n_stations)[:, None]).astype(dtype)


def _hessian_block_sums(R3, C5, Jp, Jq, p_idx, q_idx, n_stations):
    """Off-diagonal blocks + station-summed diagonal contributions from
    ONE baseline subset: R3 (T, nb, 2, 2, 2); C5 (K, T, nb, 2, 2, 2);
    Jp/Jq (K, nb, 2, 2, 2); p_idx/q_idx (nb,).  Returns
    (off (K, nb, 4, 4, 2), Dsum (K, N, 2, 2, 2)), UNNORMALIZED."""
    K, nb = C5.shape[0], C5.shape[2]

    off = -creal.einsum("ktbij,tbuv->kbiujv", creal.conj(C5), R3)
    off = off.reshape(K, nb, 4, 4, 2)

    A1 = creal.einsum("ktbuv,kbwv->ktbuw", C5, creal.conj(Jq))
    Sp = creal.einsum("ktbuw,ktbvw->kbuv", A1, creal.conj(A1))
    A2 = creal.einsum("kbuv,ktbvw->ktbuw", Jp, C5)
    Sq = creal.einsum("ktbuv,ktbuw->kbvw", creal.conj(A2), A2)

    ohp = _block_onehot(p_idx, n_stations, R3.dtype)
    ohq = _block_onehot(q_idx, n_stations, R3.dtype)
    Dsum = (jnp.einsum("nb,kbuvz->knuvz", ohp, Sp)
            + jnp.einsum("nb,kbuvz->knuvz", ohq, Sq))
    return off, Dsum


def _hessian_assemble(off, Dsum, n_stations, B, T):
    """Placement tail shared by the blocked and sharded Hessian paths:
    off (K, B, 4, 4, 2) global off-diagonal block table (zero rows where
    this caller holds no baseline), Dsum (K, N, 2, 2, 2) station sums.
    Returns (K, 4N, 4N, 2) normalized by the GLOBAL B*T."""
    K = off.shape[0]
    eye2 = jnp.eye(2, dtype=off.dtype)
    diag_blocks = jnp.einsum("knjiz,uv->kniujvz", Dsum, eye2).reshape(
        K, n_stations, 4, 4, 2)

    idx = jnp.asarray(offdiag_index_map(n_stations))
    off_pad = jnp.concatenate(
        [off, jnp.zeros((K, 1, 4, 4, 2), off.dtype)], axis=1)
    herm_pad = creal.conj(jnp.swapaxes(off_pad, -3, -2))
    Hup = off_pad[:, idx]
    Hlow = herm_pad[:, idx.T]
    eyeN = jnp.eye(n_stations, dtype=off.dtype)
    Hd = jnp.einsum("nm,knijz->knmijz", eyeN, diag_blocks)
    H = jnp.swapaxes(Hup + Hlow + Hd, 2, 3)
    N4 = 4 * n_stations
    return H.reshape(K, N4, N4, 2) / (B * T)


def _hessian_res_core_blocked_sr(R3, C5, Jp, Jq, n_stations,
                                 block_baselines):
    """Blocked :func:`_hessian_res_core_sr` on the same hoisted per-chunk
    operands: a ``lax.scan`` over baseline blocks bounds the big einsum
    temporaries to the block size.  Same math to float round-off (the
    block scan reassociates the station sums; parity tested)."""
    from jax import lax

    K, T, B = C5.shape[0], C5.shape[1], C5.shape[2]
    p_idx, q_idx = baseline_indices(n_stations)
    blk = min(int(block_baselines), B)
    nblk = -(-B // blk)
    padb = nblk * blk - B

    def pad_b(x, axis):
        pw = [(0, 0)] * x.ndim
        pw[axis] = (0, padb)
        return jnp.pad(x, pw)

    # sentinel station index for pad slots -> all-zero one-hot columns;
    # the zero-padded C5/Jones blocks make every other pad contribution 0
    pi = jnp.concatenate([p_idx, jnp.full((padb,), n_stations,
                                          p_idx.dtype)])
    qi = jnp.concatenate([q_idx, jnp.full((padb,), n_stations,
                                          q_idx.dtype)])
    R3b = jnp.moveaxis(pad_b(R3, 1).reshape(T, nblk, blk, 2, 2, 2), 1, 0)
    C5b = jnp.moveaxis(pad_b(C5, 2).reshape(K, T, nblk, blk, 2, 2, 2),
                       2, 0)
    Jpb = jnp.moveaxis(pad_b(Jp, 1).reshape(K, nblk, blk, 2, 2, 2), 1, 0)
    Jqb = jnp.moveaxis(pad_b(Jq, 1).reshape(K, nblk, blk, 2, 2, 2), 1, 0)
    pib = pi.reshape(nblk, blk)
    qib = qi.reshape(nblk, blk)

    def body(dsum, xs):
        r3, c5, jp, jq, pidx, qidx = xs
        off_b, dsum_b = _hessian_block_sums(r3, c5, jp, jq, pidx, qidx,
                                            n_stations)
        return dsum + dsum_b, off_b

    dsum0 = jnp.zeros((K, n_stations, 2, 2, 2), R3.dtype)
    Dsum, off_blocks = lax.scan(body, dsum0,
                                (R3b, C5b, Jpb, Jqb, pib, qib))
    off = jnp.moveaxis(off_blocks, 0, 1).reshape(
        K, nblk * blk, 4, 4, 2)[:, :B]
    return _hessian_assemble(off, Dsum, n_stations, B, T)


# ---------------------------------------------------------------------------
# Solution derivatives dJ/dx
# ---------------------------------------------------------------------------

_J_OF_R = np.asarray([0, 0, 0, 0, 1, 1, 1, 1])
_V_OF_R = np.asarray([0, 0, 1, 1, 0, 0, 1, 1])
_ODD_R = np.asarray([False, True] * 4)


@partial(jax.jit, static_argnames=("n_stations",))
def dsolutions_all_sr(Cs, Js, n_stations, Dgs):
    """dJ/dx for all 8 real perturbation directions r: (8, K, 4N, B, 2).

    For baseline column b (station pair p<q) the RHS column is built from
    lhs = Jq (sum_t Ci)^H and fillvex_r = kron(lhs^T, I2)[:, r//2] * phase_r
    (phase 1 for even r, i for odd r) written into rows {2p, 2p+1} and
    {2N+2p, 2N+2p+1}; then dJ_r = (Dgrad + eps I)^{-1} AdV_r, with all 8 r
    solved against one factorization per direction.
    Reference: Dsolutions_r, calibration_tools.py:778-823.
    """
    B = n_stations * (n_stations - 1) // 2
    K = Cs.shape[0]
    C5 = jnp.swapaxes(Cs.reshape(K, -1, B, 2, 2, 2), -3, -2)
    Csum = jnp.sum(C5, axis=1)                          # (K, B, 2, 2, 2)
    J4 = _jones_blocks_sr(Js, n_stations)
    p_idx, q_idx = baseline_indices(n_stations)
    Jq = J4[:, q_idx]

    lhs = creal.einsum("kbuv,kbwv->kbuw", Jq, creal.conj(Csum))  # Jq Csum^H

    # fillvex: M = kron(lhs^T, I2); column m = r//2 has entries
    # M[2i+u, m] = lhs[m//2, i] * delta_{u, m%2}; odd r multiplies by i.
    lhs_g = lhs[:, :, _J_OF_R, :, :]                    # (K, B, 8, i, 2)
    delta = jnp.eye(2, dtype=Cs.dtype)[_V_OF_R]         # (8, 2) over u
    fv = (lhs_g[:, :, :, None, :, :]                    # (K,B,8,u,i,2)
          * delta[None, None, :, :, None, None])
    fv = jnp.where(_ODD_R[None, None, :, None, None, None],
                   creal.mul_i(fv), fv)
    # reorder to (B, 8, K, i, u, 2) for the scatter
    vals = jnp.transpose(fv, (1, 2, 0, 4, 3, 5))

    AdV = jnp.zeros((8, K, 2, n_stations, 2, B, 2), dtype=Cs.dtype)
    bidx = jnp.arange(B)
    AdV = AdV.at[:, :, :, p_idx, :, bidx, :].add(vals)
    AdV = AdV.reshape(8, K, 4 * n_stations, B, 2)

    eps_eye = EPS_SINGULAR * jnp.eye(4 * n_stations, dtype=Cs.dtype)

    def solve_k(Dg_k, rhs_k):
        # rhs_k: (8, 4N, B, 2) -> one solve with 8B columns
        A = Dg_k.at[..., 0].add(eps_eye)
        rhs = jnp.moveaxis(rhs_k, 0, 1).reshape(4 * n_stations, 8 * B, 2)
        x = creal.solve(A, rhs)
        return jnp.moveaxis(x.reshape(4 * n_stations, 8, B, 2), 1, 0)

    dJ = jax.vmap(solve_k)(Dgs, jnp.swapaxes(AdV, 0, 1))
    return jnp.swapaxes(dJ, 0, 1)                       # (8, K, 4N, B, 2)


def dsolutions_all(C, J, n_stations, Dgrad):
    """Complex host-edge wrapper.  Returns (8, K, 4N, B) complex."""
    dJ = dsolutions_all_sr(creal.split(C), creal.split(J), n_stations,
                           creal.split(Dgrad))
    return creal.fuse(np.asarray(dJ))


def dsolutions(C, J, n_stations, Dgrad, r):
    """Single-r variant (reference Dsolutions, calibration_tools.py:680-725).
    Returns (K, 4N, B) complex."""
    return dsolutions_all(C, J, n_stations, Dgrad)[r]


# ---------------------------------------------------------------------------
# Residual derivatives dR/dx
# ---------------------------------------------------------------------------

def _dresiduals_lhs_sr(Cs, Js, n_stations):
    """Shared lhs blocks -(C_sum Jq^H)^T per (k, b): (K, B, 2, 2, 2)."""
    B = n_stations * (n_stations - 1) // 2
    K = Cs.shape[0]
    C5 = jnp.swapaxes(Cs.reshape(K, -1, B, 2, 2, 2), -3, -2)
    Csum = jnp.sum(C5, axis=1)
    J4 = _jones_blocks_sr(Js, n_stations)
    p_idx, q_idx = baseline_indices(n_stations)
    Jq = J4[:, q_idx]
    inner = creal.einsum("kbuv,kbwv->kbuw", Csum, creal.conj(Jq))
    return -jnp.swapaxes(inner, -3, -2), p_idx


def _dresiduals_blocks_sr(Cs, Js, n_stations, dJs):
    """Common core: per-direction fillvex blocks (8, K, B, 2, 2, B, 2)."""
    B = dJs.shape[3]
    K = Cs.shape[0]
    lhs, p_idx = _dresiduals_lhs_sr(Cs, Js, n_stations)

    # dJ rows {2p, 2p+1} and {2N+2p, 2N+2p+1}: view as (8, K, 2, N, 2, B, 2)
    dJ6 = dJs.reshape(8, K, 2, n_stations, 2, B, 2)
    rhs = dJ6[:, :, :, p_idx, :, :, :]                  # (8,K,j,B,u,c,2)
    # fillvex[2i+u, c] = sum_j lhs[i,j] rhs[j, u, c]
    return creal.einsum("kbij,rkjbuc->rkbiuc", lhs, rhs)


def _selfterm():
    """addself: dVpq_r at rows 4b + r//2, phase by parity: (8, 4, 2) f32."""
    sel = np.zeros((8, 4, 2), dtype=np.float32)
    for r in range(8):
        sel[r, r // 2, r % 2] = 1.0
    return jnp.asarray(sel)


@partial(jax.jit, static_argnames=("n_stations", "addself"))
def dresiduals_all_sr(Cs, Js, n_stations, dJs, addself=True):
    """dR (8, 4B, B, 2): residual derivatives summed over directions k,
    averaged over B*T.  Reference: Dresiduals_r, calibration_tools.py:1028-1075.
    """
    B = n_stations * (n_stations - 1) // 2
    K = Cs.shape[0]
    T = Cs.shape[1] // B
    fv = _dresiduals_blocks_sr(Cs, Js, n_stations, dJs).sum(axis=1)
    dR = fv.reshape(8, 4 * B, B, 2)
    if addself:
        sel = _selfterm() * (K * T)                     # (8, 4, 2)
        bidx = jnp.arange(B)
        rows = 4 * bidx[:, None] + jnp.arange(4)[None, :]
        dR = dR.at[:, rows, bidx[:, None], :].add(sel[:, None, :, :])
    return dR / (B * T)


def dresiduals_all(C, J, n_stations, dJ, addself=True):
    """Complex host-edge wrapper.  Returns (8, 4B, B) complex."""
    out = dresiduals_all_sr(creal.split(C), creal.split(J), n_stations,
                            creal.split(dJ), addself=addself)
    return creal.fuse(np.asarray(out))


@partial(jax.jit, static_argnames=("n_stations", "addself", "perdir"))
def dresiduals_colmeans_sr(Cs, Js, n_stations, dJs, addself=True,
                           perdir=False):
    """Column means over the row-baseline axis of dR, WITHOUT materializing
    the (8, 4B, B) residual-derivative tensor.

    Returns (8, 4, B, 2) — or (8, K, 4, B, 2) when ``perdir`` — equal to
    ``mean_b dresiduals_all_sr(...)[:, 4b+pol, :, :]`` (resp. the perdir
    variant): exactly the quantity the influence engine consumes
    (analysis_torch.py:56-76 takes column means of dR and never uses dR
    itself again).

    Key structural fact: dR's dependence on its ROW baseline b enters only
    through the station p(b) (the fillvex blocks gather dJ rows at p_idx,
    see _dresiduals_blocks_sr), so the mean over rows collapses to a
    segment-sum of the lhs blocks onto stations followed by one small
    einsum against dJ.  Memory drops from O(B^2) (the reference needs
    ``loop_in_r`` / r-chunking at LOFAR scale, Dresiduals_r
    calibration_tools.py:1028-1126: ~1 GB per chunk at N=62, B=1891) to
    O(N*B) — the dJ tensor itself is the largest operand.  This is the
    reference-scale (N=62) influence path.
    """
    B = n_stations * (n_stations - 1) // 2
    K = Cs.shape[0]
    T = Cs.shape[1] // B
    lhs, p_idx = _dresiduals_lhs_sr(Cs, Js, n_stations)  # (K, B, i, j, 2)

    # G[k, n, i, j] = sum over baselines b with p(b) = n of lhs[k, b, i, j]
    G = jax.ops.segment_sum(jnp.swapaxes(lhs, 0, 1), p_idx,
                            num_segments=n_stations)    # (N, K, i, j, 2)
    G = jnp.swapaxes(G, 0, 1)                           # (K, N, i, j, 2)

    dJ6 = dJs.reshape(8, K, 2, n_stations, 2, B, 2)     # (r,k,j,n,u,c,2)
    # float normalizers: int B^2 T overflows int32 at N >= 256
    bbt = float(B) * B * T
    bb = float(B) * B
    if perdir:
        out = creal.einsum("knij,rkjnuc->rkiuc", G, dJ6)
        out = out.reshape(8, K, 4, B, 2) / bbt
        if addself:
            # dense path: dR[r, k, 4b + r//2, b, r%2] += T (then /(B*T));
            # each column has exactly one contributing row -> mean adds 1/B^2
            sel = _selfterm() / bb                      # (8, 4, 2)
            out = out + sel[:, None, :, None, :]
    else:
        out = creal.einsum("knij,rkjnuc->riuc", G, dJ6)
        out = out.reshape(8, 4, B, 2) / bbt
        if addself:
            sel = _selfterm() * K / bb
            out = out + sel[:, :, None, :]
    return out


def _colmeans_adjoint_core_sr(lhs, Dgs, p_idx, n_stations, T,
                              addself, perdir, contract_dtype=None):
    """Adjoint-form Dsolutions -> Dresiduals column means on the PRE-BUILT
    shared lhs blocks (``lhs = Jq Csum^H``, (K, B, 2, 2, 2)).

    The influence engine consumes ONLY the column means of dR, which are
    linear functionals of dJ = A^{-1} AdV:
      colmeans = G^T dJ / (B^2 T)          (G = per-station sums of the
                                            Dresiduals lhs blocks)
    so instead of the oracle's solve against the 8B-column RHS AdV
    (15128 columns at N=62 — the dominant cost of the whole influence
    chain, measured 2.3 s per chunk on the host core) this solves the
    TRANSPOSE system
      A^T y_k = w_k                        (4 RHS per direction, one
                                            factorization shared by all
                                            8 perturbation directions)
    and contracts y against AdV's closed form.  AdV is never built
    (~180 MB at N=62): its only nonzero rows per baseline column b sit at
    station p(b) with values ``lhs[k, b, J_OF_R[r], :] * phase_r`` on the
    V_OF_R[r] polarization row, so y^T AdV collapses to a gather of y at
    p(b) plus one small einsum.  Equal to the oracle chain
    (dsolutions_all_sr -> dresiduals_colmeans_sr) to float round-off.

    The Dresiduals lhs shares the Dsolutions lhs: ``-(Csum Jq^H)^T =
    -conj(Jq Csum^H)`` — one einsum where the oracle chain computes two.

    ``contract_dtype`` (cal/precision.py ``colmeans_contract`` row):
    narrows the OPERANDS of the final Yr x Lr gather-einsum — the one
    big per-baseline contraction, linear in both operands and
    downstream of the (always-f32) transpose solve — with f32
    accumulation.  None/f32 is bit-identical to the pre-policy kernel.
    """
    N = n_stations
    B = lhs.shape[1]
    onehot_p = jnp.asarray(baseline_onehots(N, lhs.dtype)[0])

    # G[k, n, i, j] = sum over baselines b with p(b) = n of the
    # Dresiduals lhs -conj(lhs)[k, b, i, j]  (one-hot matmul, no scatter)
    G = jnp.einsum("nb,kbijz->knijz", onehot_p, -creal.conj(lhs))
    return _colmeans_from_g(G, lhs, Dgs, p_idx, N, T, B, addself, perdir,
                            contract_dtype)


def _colmeans_from_g(G, lhs, Dgs, p_idx, n_stations, T, B, addself,
                     perdir, contract_dtype):
    """G -> column means: the ONE copy of the W build, the
    eps-regularized 4-RHS transpose solve, and the Yr x Lr gather tail,
    shared by the single-device core and the baseline-sharded path
    (which differ only in how the per-station sum G was formed —
    locally vs psummed).  ``lhs``/``p_idx`` may cover a SUBSET of
    baselines; ``B`` is always the GLOBAL count."""
    N = n_stations
    K = lhs.shape[0]
    dtype = lhs.dtype
    # W[k, row(j, n, u'), (i, u)] = G[k, n, i, j] delta_{u, u'}
    eye2 = jnp.eye(2, dtype=dtype)
    W = jnp.einsum("knijz,vu->kjnviuz", G, eye2)
    W = W.reshape(K, 4 * N, 4, 2)

    eps_eye = EPS_SINGULAR * jnp.eye(4 * N, dtype=dtype)

    def solve_k(Dg_k, w_k):
        A = Dg_k.at[..., 0].add(eps_eye)
        return creal.solve(jnp.swapaxes(A, 0, 1), w_k)   # A^T y = w

    Y = jax.vmap(solve_k)(Dgs, W)                        # (K, 4N, 4, 2)
    return _colmeans_from_y(Y, lhs, p_idx, N, T, B, K, addself, perdir,
                            contract_dtype)


def _colmeans_from_y(Y, lhs, p_idx, n_stations, T, B, K, addself, perdir,
                     contract_dtype=None):
    """Post-solve tail of the adjoint column means: gather the transpose
    solutions at the (possibly shard-local) baseline stations and
    contract against the lhs blocks.  ``lhs``/``p_idx`` may cover a
    SUBSET of baselines (the baseline-sharded path); ``B`` is always the
    GLOBAL baseline count (the normalization and addself factors)."""
    N = n_stations
    # float normalizers: the int products overflow int32 at SKA scale
    # (B^2 T ~ 1.1e10 at N=256) before the weak-typed f32 conversion —
    # same f32 value as the int path at every pre-r13 scale (exact in
    # f64, then rounded identically)
    bbt = float(B) * B * T
    bb = float(B) * B
    Y6 = Y.reshape(K, 2, N, 2, 4, 2)                     # (k,j,n,u',c,2)
    Yr = Y6[:, :, p_idx][:, :, :, _V_OF_R]               # (k,j,b,r,c,2)
    Lr = lhs[:, :, _J_OF_R]                              # (k,b,r,j,2)
    if perdir:
        out = creal.einsum("kjbrc,kbrj->krcb", Yr, Lr,
                           compute_dtype=contract_dtype)
        out = jnp.moveaxis(out, 0, 1)                    # (8, K, 4, b, 2)
        out = jnp.where(_ODD_R[:, None, None, None, None],
                        creal.mul_i(out), out) / bbt
        if addself:
            sel = _selfterm() / bb
            out = out + sel[:, None, :, None, :]
    else:
        out = creal.einsum("kjbrc,kbrj->rcb", Yr, Lr,    # (8, 4, b, 2)
                           compute_dtype=contract_dtype)
        out = jnp.where(_ODD_R[:, None, None, None],
                        creal.mul_i(out), out) / bbt
        if addself:
            sel = _selfterm() * K / bb
            out = out + sel[:, :, None, :]
    return out


def _colmeans_adjoint_bshard_sr(lhs_l, Dgs, p_idx_l, n_stations, T,
                                b_total, addself, perdir, axis_name,
                                contract_dtype=None):
    """Baseline-SHARDED adjoint column means: ``lhs_l``/``p_idx_l`` are
    this shard's local baseline slice, ``Dgs`` the (already psummed,
    replicated) consensus-augmented Hessian.  The per-station sum G is
    the ONE collective (the per-direction reduction); the small 4-RHS
    transpose solve runs replicated on every shard; the final gather-
    einsum is shard-local and the returned column means cover only the
    local baselines (the caller's out_spec concatenates them back into
    global baseline order)."""
    N = n_stations
    onehot_p = _block_onehot(p_idx_l, N, lhs_l.dtype)

    G = jnp.einsum("nb,kbijz->knijz", onehot_p, -creal.conj(lhs_l))
    G = jax.lax.psum(G, axis_name)       # per-direction station reduction
    return _colmeans_from_g(G, lhs_l, Dgs, p_idx_l, N, T, b_total,
                            addself, perdir, contract_dtype)


def _llr_bshard_sr(R3l, C5l, Jpl, Jql, axis_name):
    """Baseline-sharded :func:`_llr_core_sr`: the three norms are local
    partial sums psummed over the shard axis — same math as the local
    core on the concatenated operands (addition reassociated)."""
    tmp = creal.einsum("kbuv,ktbvw->ktbuw", Jpl, C5l)
    mu = creal.einsum("ktbuw,kbxw->ktbux", tmp, creal.conj(Jql))

    sV = 0.5 * (R3l[..., 0, 1, :] - R3l[..., 1, 0, :])
    sigma2 = jax.lax.psum(jnp.sum(creal.abs2(sV)), axis_name)
    rn2 = jax.lax.psum(jnp.sum(creal.abs2(R3l)), axis_name)
    rpmu2 = jax.lax.psum(
        jnp.sum(creal.abs2(R3l[None] + mu), axis=(1, 2, 3, 4)), axis_name)
    return (rpmu2 - rn2) / (sigma2 + EPS_DIV)


@partial(jax.jit, static_argnames=("n_stations", "addself", "perdir",
                                   "precision"))
def influence_colmeans_opt_sr(Cs, Js, n_stations, Dgs, addself=False,
                              perdir=False, precision="f32"):
    """Fused Dsolutions -> Dresiduals column means (8, 4, B, 2) — or
    (8, K, 4, B, 2) when ``perdir`` — straight from the coherencies,
    Jones solutions, and the (consensus-augmented) Hessian ``Dgs``.

    The production influence-path kernel: the adjoint formulation (see
    :func:`_colmeans_adjoint_core_sr`) replaces the oracle chain's
    8B-column solve with a 4-column transpose solve and drops both the
    AdV RHS and the dJ tensor.  ``dsolutions_all_sr`` +
    ``dresiduals_colmeans_sr`` are retained as the parity oracles.

    ``precision`` (static, cal/precision.py): "bf16" narrows the final
    gather-einsum operands under the ``colmeans_contract`` policy row
    (the transpose solve stays pinned f32 under every policy)."""
    B = n_stations * (n_stations - 1) // 2
    K = Cs.shape[0]
    T = Cs.shape[1] // B
    C5 = jnp.swapaxes(Cs.reshape(K, -1, B, 2, 2, 2), -3, -2)
    Csum = jnp.sum(C5, axis=1)
    J4 = _jones_blocks_sr(Js, n_stations)
    p_idx, q_idx = baseline_indices(n_stations)
    lhs = creal.einsum("kbuv,kbwv->kbuw", J4[:, q_idx], creal.conj(Csum))
    dt = _precision.contraction_dtype("colmeans_contract", precision)
    return _colmeans_adjoint_core_sr(
        lhs, Dgs, p_idx, n_stations, T, addself, perdir,
        contract_dtype=None if dt == _precision.F32 else dt)


@partial(jax.jit, static_argnames=("n_stations", "addself"))
def dresiduals_all_perdir_sr(Cs, Js, n_stations, dJs, addself=True):
    """dR (8, K, 4B, B, 2): per-direction variant.
    Reference: Dresiduals_rk, calibration_tools.py:1129-1176."""
    B = n_stations * (n_stations - 1) // 2
    T = Cs.shape[1] // B
    fv = _dresiduals_blocks_sr(Cs, Js, n_stations, dJs)
    K = fv.shape[1]
    dR = fv.reshape(8, K, 4 * B, B, 2)
    if addself:
        sel = _selfterm() * T
        bidx = jnp.arange(B)
        rows = 4 * bidx[:, None] + jnp.arange(4)[None, :]
        dR = dR.at[:, :, rows, bidx[:, None], :].add(sel[:, None, None, :, :])
    return dR / (B * T)


def dresiduals_all_perdir(C, J, n_stations, dJ, addself=True):
    """Complex host-edge wrapper.  Returns (8, K, 4B, B) complex."""
    out = dresiduals_all_perdir_sr(creal.split(C), creal.split(J), n_stations,
                                   creal.split(dJ), addself=addself)
    return creal.fuse(np.asarray(out))


def dresiduals(C, J, n_stations, dJ_r, addself, r):
    """Single-r variant (reference Dresiduals, calibration_tools.py:879-925).
    ``dJ_r`` is the (K, 4N, B) complex slice for this r.  Returns (4B, B)."""
    dJ_full = np.zeros((8,) + dJ_r.shape, dJ_r.dtype)
    dJ_full[r] = dJ_r
    full = dresiduals_all(C, J, n_stations, dJ_full, addself=False)[r]
    if addself:
        B = n_stations * (n_stations - 1) // 2
        K = C.shape[0]
        T = C.shape[1] // B
        sel = creal.fuse(np.asarray(_selfterm()))[r] * (K * T) / (B * T)
        full = np.asarray(full)
        for b in range(B):
            full[4 * b:4 * b + 4, b] += sel
    return full


# ---------------------------------------------------------------------------
# Log-likelihood-ratio detector
# ---------------------------------------------------------------------------

def _llr_core_sr(R3, C5, Jp, Jq):
    """LLR body on pre-split operands (shared by the jitted wrapper and
    the influence engine's hoisted chunk path — bit-identical math)."""
    tmp = creal.einsum("kbuv,ktbvw->ktbuw", Jp, C5)
    mu = creal.einsum("ktbuw,kbxw->ktbux", tmp, creal.conj(Jq))

    sV = 0.5 * (R3[..., 0, 1, :] - R3[..., 1, 0, :])
    sigma2 = jnp.sum(creal.abs2(sV))
    rn2 = jnp.sum(creal.abs2(R3))
    rpmu2 = jnp.sum(creal.abs2(R3[None] + mu), axis=(1, 2, 3, 4))
    return (rpmu2 - rn2) / (sigma2 + EPS_DIV)


@partial(jax.jit, static_argnames=("n_stations",))
def log_likelihood_ratio_sr(Rs, Cs, Js, n_stations):
    """Per-direction LLR (K,): (||r+mu||^2 - ||r||^2) / sigma^2 with
    mu = Jp C Jq^H per sample and sigma^2 estimated from Stokes V of the
    residual.  Reference: calibration_tools.py:1181-1223."""
    R3, C5, B, T, K = _split_samples_sr(Rs, Cs, n_stations)
    J4 = _jones_blocks_sr(Js, n_stations)
    p_idx, q_idx = baseline_indices(n_stations)
    return _llr_core_sr(R3, C5, J4[:, p_idx], J4[:, q_idx])


def log_likelihood_ratio(R, C, J, n_stations):
    """Complex host-edge wrapper.  Returns (K,) float32."""
    return np.asarray(log_likelihood_ratio_sr(
        creal.split(R), creal.split(C), creal.split(J), n_stations))
