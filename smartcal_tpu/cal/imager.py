"""Dirty imaging: visibilities -> sky image.

In-framework replacement for the reference's external ``excon`` imager
(C++, invoked at ``calibration/dosimul.sh:29``, ``docal.sh:15``,
``doinfluence.sh:8``) and the ``calmean.sh`` FITS averaging script.  The
RL envs only consume small dirty images (128x128) and their noise
statistics (``calibenv.py:148-166``), so a deconvolution-free imager is the
whole requirement.

TPU-first design: instead of scatter-add uv gridding + FFT (sequential
scatter, complex dtypes), the image is a DIRECT DFT onto the pixel grid —
two real matmuls of shape (npix^2, nvis): exactly the large, batched,
bf16-able contraction the MXU is built for, with no complex lowering and no
data-dependent gather/scatter.  At the envs' scales (~1e4 pixels x ~1e5
visibilities) this is a few GFLOP — microseconds on the MXU, far below the
host cost the reference pays to shell out and read FITS back.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

C_LIGHT = 2.99792458e8


def pixel_grid(npix, cell):
    """(npix^2, 2) direction cosines (l, m) of the image pixels; row-major
    with m varying fastest; centered, north up (m increasing)."""
    half = npix // 2
    idx = (jnp.arange(npix) - half).astype(jnp.float32) * cell
    ll, mm = jnp.meshgrid(idx, idx, indexing="ij")
    return jnp.stack([ll.ravel(), mm.ravel()], axis=-1)


def default_cell(uvw, freq, oversample=3.0):
    """Pixel size (rad) from the longest projected baseline:
    cell = 1 / (oversample * 2 * max|uv|_wavelengths)."""
    uv = np.asarray(uvw)[..., :2] * (float(freq) / C_LIGHT)
    umax = float(np.max(np.abs(uv)))
    return 1.0 / (oversample * 2.0 * max(umax, 1.0))


def dirty_image_sr(uvw, vis, freq, cell, npix=128):
    """Dirty image (npix, npix) from split-real Stokes visibilities.

    uvw : (R, 3) meters;  vis : (R, 2) split-real complex samples
    I(l, m) = mean_r Re( V_r exp(i phase) ),  phase = scale (u l + v m)

    Dispatches to the fused Pallas kernel on TPU for aligned image sizes
    (ops/pallas_imager.py: the (P, R) phase/trig intermediates never
    leave VMEM), the XLA formulation otherwise.  Callers inside a
    GSPMD-sharded program must use :func:`dirty_image_sr_xla` directly —
    pallas_call has no partitioning rule.
    """
    from smartcal_tpu.ops import pallas_imager  # lazy: ops is above cal

    if ((npix * npix) % pallas_imager.TILE_P == 0
            and pallas_imager.pallas_available()):
        return pallas_imager.dirty_image_pallas(uvw, vis, freq, cell,
                                                npix=npix)
    return dirty_image_sr_xla(uvw, vis, freq, cell, npix=npix)


@partial(jax.jit, static_argnames=("npix",))
def dirty_image_factored_sr(uvw, vis, freq, cell, npix=128):
    """Rank-factored DFT image — the influence-path production imager.

    The pixel grid is separable (l indexes rows, m columns), so the DFT
    phase splits: ``cos/sin(l u + m v)`` expands over the axis planes
    ``a = l u`` and ``b = m v`` via the angle-addition identity, and the
    image becomes TWO (npix, R) @ (R, npix) matmuls over per-axis
    weighted visibilities:
      img = (cos a * Vr + sin a * Vi) @ cos(b)^T
          + (cos a * Vi - sin a * Vr) @ sin(b)^T,   then / R.
    Versus :func:`dirty_image_sr_xla` (retained as the parity oracle and
    the golden for the Pallas kernel) this drops the transcendental count
    from 2 P R to 4 npix R (64x at npix=128) and the largest intermediate
    from (P, R) — 2.4 GB at the N=62 episode scale, where it measured
    ~17 s per sub-band on the host core — to (npix, R): same math to
    float round-off (the identity reassociates the phase evaluation).
    Pure matmuls + elementwise: safe inside GSPMD/shard_map programs.
    """
    scale = 2.0 * jnp.pi * freq / C_LIGHT
    u = uvw[:, 0] * scale
    v = uvw[:, 1] * scale
    half = npix // 2
    idx = (jnp.arange(npix) - half).astype(jnp.float32) * cell
    a = idx[:, None] * u[None, :]                          # (npix, R) l u
    b = idx[:, None] * v[None, :]                          # (npix, R) m v
    ca, sa = jnp.cos(a), jnp.sin(a)
    cb, sb = jnp.cos(b), jnp.sin(b)
    vr, vi = vis[:, 0], vis[:, 1]
    p1 = ca * vr[None, :] + sa * vi[None, :]
    p2 = ca * vi[None, :] - sa * vr[None, :]
    img = p1 @ cb.T + p2 @ sb.T                            # (l, m)
    return img / vis.shape[0]


@partial(jax.jit, static_argnames=("npix",))
def dirty_image_sr_xla(uvw, vis, freq, cell, npix=128):
    """Plain XLA formulation (materializes the (P, R) phase matrix); the
    safe path inside sharded jits and the golden oracle for the kernel."""
    scale = 2.0 * jnp.pi * freq / C_LIGHT
    uv = uvw[:, :2] * scale                                # (R, 2)
    lm = pixel_grid(npix, cell)                            # (P, 2)
    phase = lm @ uv.T                                      # (P, R) matmul 1
    # Re(V conj(exp(i phase))): the prediction direction is V ~ exp(+i phase)
    # (cal/coherency._predict), so imaging applies the conjugate kernel
    re = jnp.cos(phase) @ vis[:, 0] + jnp.sin(phase) @ vis[:, 1]  # matmul 2
    img = re / vis.shape[0]
    return img.reshape(npix, npix)


def stokes_i_vis(V):
    """(T, B, 2, 2, 2) full-pol solver visibilities -> (T*B, 2) Stokes I."""
    sI = 0.5 * (V[..., 0, 0, :] + V[..., 1, 1, :])
    return sI.reshape(-1, 2)


@partial(jax.jit, static_argnames=("npix",))
def image_observation_sr(uvw, V, freq, cell, npix=128):
    """Dirty Stokes-I image of solver-convention visibilities
    (uvw (T, B, 3), V (T, B, 2, 2, 2))."""
    return dirty_image_sr(uvw.reshape(-1, 3), stokes_i_vis(V), freq, cell,
                          npix=npix)


def multifreq_image_sr(uvw, V_list, freqs, cell, npix=128):
    """Average dirty image over frequency sub-bands (the role of
    ``calmean.sh``'s weighted FITS mean, calibration/calmean.sh:1-100).
    V_list: (Nf, T, B, 2, 2, 2); uvw shared across sub-bands (meters)."""
    imgs = jax.vmap(
        lambda v, f: image_observation_sr(uvw, v, f, cell, npix=npix)
    )(V_list, jnp.asarray(freqs))
    return jnp.mean(imgs, axis=0)


def image_noise_std(img):
    """sigma of an image, the env observation statistic
    (calibenv.py:148-166 reads np.std of FITS data)."""
    return jnp.std(img)


def image_to_fits(path, img, obs, freq=None, cell=None, **kw):
    """Write a device image to a radio FITS file with the observation's
    WCS (the excon-output contract a reference user expects; headers per
    cal/fits_io.write_image).  ``freq`` defaults to the highest sub-band
    (the one default_cell sizes pixels for), ``cell`` to default_cell."""
    from smartcal_tpu.cal import fits_io

    freqs = np.asarray(obs.freqs)
    freq = float(freqs[-1]) if freq is None else float(freq)
    cell = (float(default_cell(obs.uvw, freq)) if cell is None
            else float(cell))
    return fits_io.write_image(path, np.asarray(img), ra0=float(obs.ra0),
                               dec0=float(obs.dec0), cell_rad=cell,
                               freq=freq, **kw)
