"""Dirty imaging: visibilities -> sky image.

In-framework replacement for the reference's external ``excon`` imager
(C++, invoked at ``calibration/dosimul.sh:29``, ``docal.sh:15``,
``doinfluence.sh:8``) and the ``calmean.sh`` FITS averaging script.  The
RL envs only consume small dirty images (128x128) and their noise
statistics (``calibenv.py:148-166``), so a deconvolution-free imager is the
whole requirement.

TPU-first design: instead of scatter-add uv gridding + FFT (sequential
scatter, complex dtypes), the image is a DIRECT DFT onto the pixel grid —
two real matmuls of shape (npix^2, nvis): exactly the large, batched,
bf16-able contraction the MXU is built for, with no complex lowering and no
data-dependent gather/scatter.  At the envs' scales (~1e4 pixels x ~1e5
visibilities) this is a few GFLOP — microseconds on the MXU, far below the
host cost the reference pays to shell out and read FITS back.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from smartcal_tpu.cal import precision as prec

C_LIGHT = 2.99792458e8


def pixel_grid(npix, cell):
    """(npix^2, 2) direction cosines (l, m) of the image pixels; row-major
    with m varying fastest; centered, north up (m increasing)."""
    half = npix // 2
    idx = (jnp.arange(npix) - half).astype(prec.F32) * cell
    ll, mm = jnp.meshgrid(idx, idx, indexing="ij")
    return jnp.stack([ll.ravel(), mm.ravel()], axis=-1)


def default_cell(uvw, freq, oversample=3.0):
    """Pixel size (rad) from the longest projected baseline:
    cell = 1 / (oversample * 2 * max|uv|_wavelengths)."""
    uv = np.asarray(uvw)[..., :2] * (float(freq) / C_LIGHT)
    umax = float(np.max(np.abs(uv)))
    return 1.0 / (oversample * 2.0 * max(umax, 1.0))


def dirty_image_sr(uvw, vis, freq, cell, npix=128):
    """Dirty image (npix, npix) from split-real Stokes visibilities.

    uvw : (R, 3) meters;  vis : (R, 2) split-real complex samples
    I(l, m) = mean_r Re( V_r exp(i phase) ),  phase = scale (u l + v m)

    Dispatches to the fused Pallas kernel on TPU for aligned image sizes
    (ops/pallas_imager.py: the (P, R) phase/trig intermediates never
    leave VMEM), the XLA formulation otherwise.  Callers inside a
    GSPMD-sharded program must use :func:`dirty_image_sr_xla` directly —
    pallas_call has no partitioning rule.
    """
    from smartcal_tpu.ops import pallas_imager  # lazy: ops is above cal

    if ((npix * npix) % pallas_imager.TILE_P == 0
            and pallas_imager.pallas_available()):
        return pallas_imager.dirty_image_pallas(uvw, vis, freq, cell,
                                                npix=npix)
    return dirty_image_sr_xla(uvw, vis, freq, cell, npix=npix)


def _factored_planes(uvw, vis, freq, cell, npix):
    """Shared (p1, p2, cb, sb) plane build of the factored DFT imager
    (see :func:`dirty_image_factored_sr`); phase/trig stays f32 — the
    range-reduction-sensitive part of the formulation."""
    scale = 2.0 * jnp.pi * freq / C_LIGHT
    u = uvw[:, 0] * scale
    v = uvw[:, 1] * scale
    half = npix // 2
    idx = (jnp.arange(npix) - half).astype(prec.F32) * cell
    a = idx[:, None] * u[None, :]                          # (npix, R) l u
    b = idx[:, None] * v[None, :]                          # (npix, R) m v
    ca, sa = jnp.cos(a), jnp.sin(a)
    cb, sb = jnp.cos(b), jnp.sin(b)
    vr, vi = vis[:, 0], vis[:, 1]
    p1 = ca * vr[None, :] + sa * vi[None, :]
    p2 = ca * vi[None, :] - sa * vr[None, :]
    return p1, p2, cb, sb


def _factored_contract(p1, p2, cb, sb, dt):
    """The two (npix, R) @ (R, npix) matmuls, with operands narrowed to
    the policy dtype ``dt`` and f32 accumulation (the mixed-precision
    MXU shape; dt == f32 is bit-identical to the plain matmuls)."""
    kw = {}
    if dt != prec.F32:
        # pin f32 accumulation even if the operands already arrive in
        # the compute dtype — same contract as creal.einsum
        kw["preferred_element_type"] = prec.F32
        if dt != p1.dtype:
            p1, p2 = p1.astype(dt), p2.astype(dt)
            cb, sb = cb.astype(dt), sb.astype(dt)
    return jnp.matmul(p1, cb.T, **kw) + jnp.matmul(p2, sb.T, **kw)


@partial(jax.jit, static_argnames=("npix", "precision"))
def dirty_image_factored_sr(uvw, vis, freq, cell, npix=128,
                            precision="f32"):
    """Rank-factored DFT image — the influence-path production imager.

    The pixel grid is separable (l indexes rows, m columns), so the DFT
    phase splits: ``cos/sin(l u + m v)`` expands over the axis planes
    ``a = l u`` and ``b = m v`` via the angle-addition identity, and the
    image becomes TWO (npix, R) @ (R, npix) matmuls over per-axis
    weighted visibilities:
      img = (cos a * Vr + sin a * Vi) @ cos(b)^T
          + (cos a * Vi - sin a * Vr) @ sin(b)^T,   then / R.
    Versus :func:`dirty_image_sr_xla` (retained as the parity oracle and
    the golden for the Pallas kernel) this drops the transcendental count
    from 2 P R to 4 npix R (64x at npix=128) and the largest intermediate
    from (P, R) — 2.4 GB at the N=62 episode scale, where it measured
    ~17 s per sub-band on the host core — to (npix, R): same math to
    float round-off (the identity reassociates the phase evaluation).
    Pure matmuls + elementwise: safe inside GSPMD/shard_map programs.

    ``precision`` (static, cal/precision.py): "bf16" narrows the matmul
    OPERANDS under the ``imager_matmul`` policy row (f32 accumulation;
    phase/trig untouched) — measured image parity within the documented
    bf16 tolerance in tests/test_nscale_kernels.py; "f32" (default) is
    bit-identical to the pre-policy kernel.
    """
    dt = prec.contraction_dtype("imager_matmul", precision)
    p1, p2, cb, sb = _factored_planes(uvw, vis, freq, cell, npix)
    return _factored_contract(p1, p2, cb, sb, dt) / vis.shape[0]


@partial(jax.jit, static_argnames=("npix", "block_r", "precision"))
def dirty_image_factored_blocked_sr(uvw, vis, freq, cell, npix=1024,
                                    block_r=4096, precision="f32"):
    """BLOCKED rank-factored DFT image — the npix>=1024 / B~N^2 tier.

    At SKA scale the factored imager's (npix, R) planes stop being
    small: npix=1024 x R = T*B(N=256) ~ 6.5e5 is ~2.7 GB PER PLANE (six
    live at once).  Here the visibility axis is tiled: a ``lax.scan``
    over R-blocks accumulates the (npix, npix) image, so the largest
    live buffer is a (npix, block_r) plane (~16 MB at the default
    block) plus the f32 image accumulator — the blocked-kernel memory
    contract.  Transcendental count and math are IDENTICAL to
    :func:`dirty_image_factored_sr` (the R-axis sum is reassociated
    across blocks; parity tested to float round-off), so this is the
    ``lax`` fallback of the tiled Pallas kernel
    (ops/pallas_imager.dirty_image_factored_pallas) on CPU/GPU and
    inside GSPMD programs.

    R is zero-padded to the block size (padded vis rows are 0, so any
    phase value contributes nothing — the pallas_imager convention).
    """
    dt = prec.contraction_dtype("imager_matmul", precision)
    R = uvw.shape[0]
    nblk = -(-R // block_r)
    padr = nblk * block_r - R
    uv = jnp.pad(uvw[:, :2], ((0, padr), (0, 0)))
    vp = jnp.pad(vis, ((0, padr), (0, 0)))
    uvb = uv.reshape(nblk, block_r, 2)
    vb = vp.reshape(nblk, block_r, 2)

    def body(acc, operand):
        uvw_b, vis_b = operand
        uvw3 = jnp.pad(uvw_b, ((0, 0), (0, 1)))   # w unused by the planes
        p1, p2, cb, sb = _factored_planes(uvw3, vis_b, freq, cell, npix)
        return acc + _factored_contract(p1, p2, cb, sb, dt), None

    img0 = jnp.zeros((npix, npix), prec.F32)
    img, _ = lax.scan(body, img0, (uvb, vb))
    return img / vis.shape[0]


def dirty_image_factored_large_sr(uvw, vis, freq, cell, npix=1024,
                                  block_r=4096, precision="f32",
                                  allow_pallas=True):
    """Dispatcher for the npix >= 512 factored-imager tier: the tiled
    Pallas kernel on TPU for aligned image sizes (the (TILE_L, TILE_M,
    TILE_R) VMEM-tile twin — ops/pallas_imager.dirty_image_factored_
    pallas), the R-blocked lax kernel otherwise — the same
    dispatch-upgrades-every-caller contract as :func:`dirty_image_sr`.
    Callers INSIDE a GSPMD/shard_map program pass
    ``allow_pallas=False`` (pallas_call has no partitioning rule)."""
    from smartcal_tpu.ops import pallas_imager  # lazy: ops is above cal

    if (allow_pallas and npix % pallas_imager.TILE_L == 0
            and pallas_imager.pallas_available()):
        return pallas_imager.dirty_image_factored_pallas(
            uvw, vis, freq, cell, npix=npix, precision=precision)
    return dirty_image_factored_blocked_sr(uvw, vis, freq, cell,
                                           npix=npix, block_r=block_r,
                                           precision=precision)


@partial(jax.jit, static_argnames=("npix",))
def dirty_image_sr_xla(uvw, vis, freq, cell, npix=128):
    """Plain XLA formulation (materializes the (P, R) phase matrix); the
    safe path inside sharded jits and the golden oracle for the kernel."""
    scale = 2.0 * jnp.pi * freq / C_LIGHT
    uv = uvw[:, :2] * scale                                # (R, 2)
    lm = pixel_grid(npix, cell)                            # (P, 2)
    phase = lm @ uv.T                                      # (P, R) matmul 1
    # Re(V conj(exp(i phase))): the prediction direction is V ~ exp(+i phase)
    # (cal/coherency._predict), so imaging applies the conjugate kernel
    re = jnp.cos(phase) @ vis[:, 0] + jnp.sin(phase) @ vis[:, 1]  # matmul 2
    img = re / vis.shape[0]
    return img.reshape(npix, npix)


def stokes_i_vis(V):
    """(T, B, 2, 2, 2) full-pol solver visibilities -> (T*B, 2) Stokes I."""
    sI = 0.5 * (V[..., 0, 0, :] + V[..., 1, 1, :])
    return sI.reshape(-1, 2)


@partial(jax.jit, static_argnames=("npix",))
def image_observation_sr(uvw, V, freq, cell, npix=128):
    """Dirty Stokes-I image of solver-convention visibilities
    (uvw (T, B, 3), V (T, B, 2, 2, 2))."""
    return dirty_image_sr(uvw.reshape(-1, 3), stokes_i_vis(V), freq, cell,
                          npix=npix)


def multifreq_image_sr(uvw, V_list, freqs, cell, npix=128):
    """Average dirty image over frequency sub-bands (the role of
    ``calmean.sh``'s weighted FITS mean, calibration/calmean.sh:1-100).
    V_list: (Nf, T, B, 2, 2, 2); uvw shared across sub-bands (meters)."""
    imgs = jax.vmap(
        lambda v, f: image_observation_sr(uvw, v, f, cell, npix=npix)
    )(V_list, jnp.asarray(freqs))
    return jnp.mean(imgs, axis=0)


def image_noise_std(img):
    """sigma of an image, the env observation statistic
    (calibenv.py:148-166 reads np.std of FITS data)."""
    return jnp.std(img)


def image_to_fits(path, img, obs, freq=None, cell=None, **kw):
    """Write a device image to a radio FITS file with the observation's
    WCS (the excon-output contract a reference user expects; headers per
    cal/fits_io.write_image).  ``freq`` defaults to the highest sub-band
    (the one default_cell sizes pixels for), ``cell`` to default_cell."""
    from smartcal_tpu.cal import fits_io

    freqs = np.asarray(obs.freqs)
    freq = float(freqs[-1]) if freq is None else float(freq)
    cell = (float(default_cell(obs.uvw, freq)) if cell is None
            else float(cell))
    return fits_io.write_image(path, np.asarray(img), ra0=float(obs.ra0),
                               dec0=float(obs.dec0), cell_rad=cell,
                               freq=freq, **kw)
