"""Influence-map engine: residual sensitivity to data perturbations.

Parity targets:
  * ``calibration/analysis_torch.py:16-186`` (analysis_uvwdir_loop +
    process_chunk) — summed-over-directions influence visibilities,
  * ``calibration/analysis.py:16-183`` — the numpy twin,
  * ``calibration/influence_tools.py:219-372`` (analysis_uvw_perdir) —
    per-direction influence + ||J||, ||C||, |mean Inf|, LLR metadata.

Algorithm per calibration interval (chunk of Tdelta timeslots):
  H  = Hessianres(R, C, J) + Hadd(consensus)        (cal/kernels.py)
  dJ = Dsolutions_r(C, J, H)   — 8 perturbation directions
  dR = Dresiduals_r(C, J, dJ)
  influence per baseline = sum_r column-means of dR's XX/YY rows,
  replicated over the interval's timeslots, scaled by 8*B*Tdelta.
The result is written back as "visibilities" and imaged (cal/imager.py) to
produce the influence map the RL envs observe (calibenv.py:148-166).

TPU-first design: the reference forks a multiprocessing pool over chunks
with shared-memory tensors (analysis_torch.py:160-170); here chunks are a
``lax.map`` axis inside one jit — sharding the chunk axis over devices is a
``shard_map`` away.  The consensus Hessian addition Hadd collapses to a
SCALAR per direction (the reference's dense F and P'P are both multiples of
I_2N — see consensus_hadd_scalars), so no 4N x 4N dense prior is built.

Memory note: the engine consumes only the COLUMN MEANS of dR, so the
(8, 4B, B) tensor — ~1 GB per chunk at LOFAR scale (N=62, B=1891), the
reason the reference needs its ``loop_in_r`` r-chunking — is never
materialized here: kernels.dresiduals_colmeans_sr reduces the row axis
analytically (segment-sum onto stations + one einsum against dJ), leaving
the (8, K, 4N, B) dJ tensor as the largest buffer (~180 MB at N=62).  The
dense dresiduals_all_sr kernels remain as the golden-test oracles and the
API for consumers that need the full derivative tensor.
"""

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from smartcal_tpu.cal import consensus, kernels
from smartcal_tpu.cal import precision as prec


def consensus_hadd_scalars(rho_spectral, rho_spatial, freqs, f0, fidx,
                           n_poly=2, polytype=1):
    """Per-direction consensus Hessian addition, as the scalar h_k with
    Hadd_k = h_k * I_4N.

    The reference builds dense matrices (analysis_torch.py:141-156) from
    F = fscale*I and P with P'P = pp*I (consensus_poly, cal/consensus.py),
    so both branches reduce to scalars:
      alpha > 0 (spatial regularization, Schur complement):
        H11 = rho/2 fs^2 + alpha rho^2 pp / 2
        H12 = fs^2/2 + alpha rho pp / 2
        H22 = -(1 - fs^2)/(2 rho) + alpha pp / 2
        h   = H11 - H12^2 / H22
      alpha == 0:
        h = rho/2 * fs^2 * (1 + fs^2 / (1 - fs^2))
    """
    freqs = jnp.asarray(freqs, prec.F32)
    rho = jnp.asarray(rho_spectral, prec.F32)
    alpha = jnp.asarray(rho_spatial, prec.F32)

    def per_dir(r, a):
        bfull, bi, fscale = consensus.consensus_cores(
            freqs, f0, n_poly, polytype, rho=r, alpha=a)
        fs2 = fscale[fidx] ** 2
        bf = bfull[fidx]
        # P = kron(Bi b_f, I); P'P = ||Bi b_f||^2 I
        pp = jnp.sum((bi @ bf) ** 2)
        h11 = 0.5 * r * fs2 + 0.5 * a * r * r * pp
        h12 = 0.5 * fs2 + 0.5 * a * r * pp
        h22 = -0.5 / r * (1.0 - fs2) + 0.5 * a * pp
        h_spatial = h11 - h12 * h12 / jnp.where(h22 == 0, 1.0, h22)
        denom = jnp.where(jnp.abs(1.0 - fs2) < 1e-12, 1.0, 1.0 - fs2)
        h_plain = 0.5 * r * fs2 * (1.0 + fs2 / denom)
        return jnp.where(a > 0.0, h_spatial, h_plain)

    return jax.vmap(per_dir)(rho, alpha)


def consensus_hadd_all(rho_spectral, rho_spatial, freqs, f0, n_poly=2,
                       polytype=1):
    """(Nf, K) consensus scalars for EVERY sub-band in one call — the
    vmapped form of :func:`consensus_hadd_scalars` over the frequency
    index, so multi-band influence consumers pay one device dispatch
    instead of Nf."""
    nf = jnp.asarray(freqs).shape[0]
    return jax.vmap(lambda fi: consensus_hadd_scalars(
        rho_spectral, rho_spatial, freqs, f0, fi, n_poly=n_poly,
        polytype=polytype))(jnp.arange(nf))


class InfluenceResult(NamedTuple):
    vis: jnp.ndarray   # (T*B, 4, 2) influence visibilities [XX, XY, YX, YY]
    llr: jnp.ndarray   # (Ts, K) per-chunk log-likelihood ratios


def _chunk_influence(R, C, J, hadd, n_stations, fullpol, perdir):
    """One calibration interval, ORACLE formulation.  R (2*B*Td, 2, 2);
    C (K, B*Td, 4, 2); J (K, 2N, 2, 2); hadd (K,).  Returns (vis_b, llr)
    where vis_b is (B, 4, 2) [or (K, B, 4, 2) per-direction].

    Retained as the parity oracle for the optimized chunk path below
    (``optimized=False`` routes here): per-kernel split-real rebuilds,
    scatter-based Hessian, and the 8B-column Dsolutions solve."""
    H = kernels.hessian_res_sr(R, C, J, n_stations)
    N4 = H.shape[1]
    H = H.at[:, jnp.arange(N4), jnp.arange(N4), 0].add(hadd[:, None])
    dJ = kernels.dsolutions_all_sr(C, J, n_stations, H)
    # fused column means: never materializes the (8, [K,] 4B, B) dR tensor
    # (kernels.dresiduals_colmeans_sr) — the memory move that makes the
    # LOFAR-scale regime (N=62, B=1891) fit in HBM without r-chunking
    pol_means = kernels.dresiduals_colmeans_sr(C, J, n_stations, dJ,
                                               addself=False, perdir=perdir)
    return _chunk_post(pol_means, fullpol), \
        kernels.log_likelihood_ratio_sr(R, C, J, n_stations)


def _chunk_post(pol_means, fullpol):
    vis = jnp.sum(pol_means, axis=0)          # (K, 4, B, 2) or (4, B, 2)
    vis = jnp.swapaxes(vis, -3, -2)           # (K, B, 4, 2) or (B, 4, 2)
    if not fullpol:
        vis = vis.at[..., 1, :].set(0.0).at[..., 2, :].set(0.0)
    return vis


def _chunk_influence_opt(R3, C5, Jp, Jq, lhs, hadd, n_stations, fullpol,
                         perdir, block_baselines=0, precision="f32",
                         use_pallas=False):
    """One calibration interval, OPTIMIZED formulation, on hoisted
    operands: the split-real block forms (R3, C5), the station-gathered
    Jones blocks (Jp, Jq) and the shared Dsolutions/Dresiduals lhs are
    built ONCE for all chunks by the caller (the oracle chain rebuilds
    each of them per chunk per kernel).  Hessian is the scatter-free
    formulation; the Dsolutions -> Dresiduals chain is the adjoint
    4-RHS transpose solve (kernels._colmeans_adjoint_core_sr).

    ``block_baselines`` (static) > 0 selects the BLOCKED Hessian core
    (kernels._hessian_res_core_blocked_sr — a lax.scan over baseline
    blocks bounding the einsum temporaries to the block, the B ~ N^2
    memory tier); ``precision`` (static, cal/precision.py) narrows the
    colmeans contraction under the ``colmeans_contract`` policy row —
    the Hessian build and the transpose solve stay pinned f32.

    ``use_pallas`` (static) promotes the blocked tier to the tiled
    Mosaic kernel (ops/pallas_hessian.hessian_res_core_pallas_sr) when
    the backend is a TPU — the SAME static-threshold routing as the
    blocked XLA core, one more rung on the ladder; CPU/GPU and sharded
    callers fall through to the lax.scan twin."""
    Td = C5.shape[1]
    p_idx, _ = kernels.baseline_indices(n_stations)
    if block_baselines:
        from smartcal_tpu.ops import pallas_hessian  # lazy: ops is optional
        if use_pallas and pallas_hessian.pallas_available():
            H = pallas_hessian.hessian_res_core_pallas_sr(
                R3, C5, Jp, Jq, n_stations)
        else:
            H = kernels._hessian_res_core_blocked_sr(R3, C5, Jp, Jq,
                                                     n_stations,
                                                     block_baselines)
    else:
        H = kernels._hessian_res_core_sr(R3, C5, Jp, Jq, n_stations)
    N4 = H.shape[1]
    H = H.at[:, jnp.arange(N4), jnp.arange(N4), 0].add(hadd[:, None])
    dt = prec.contraction_dtype("colmeans_contract", precision)
    pol_means = kernels._colmeans_adjoint_core_sr(
        lhs, H, p_idx, n_stations, Td, addself=False, perdir=perdir,
        contract_dtype=None if dt == prec.F32 else dt)
    return _chunk_post(pol_means, fullpol), \
        kernels._llr_core_sr(R3, C5, Jp, Jq)


@partial(jax.jit, static_argnames=("n_stations", "n_chunks", "fullpol",
                                   "perdir", "optimized",
                                   "block_baselines", "precision",
                                   "use_pallas"))
def influence_visibilities(R, C, J, hadd, n_stations, n_chunks,
                           fullpol=False, perdir=False,
                           optimized=True, block_baselines=0,
                           precision="f32",
                           use_pallas=True) -> InfluenceResult:
    """Influence visibilities over all calibration intervals.

    R : (2*B*T, 2, 2) kernel-convention residuals for one sub-band
    C : (K, T*B, 4, 2) coherencies
    J : (Ts, K, 2N, 2, 2) per-interval solutions (Ts = n_chunks)
    hadd : (K,) consensus scalars (consensus_hadd_scalars)

    Returns vis (T*B, 4, 2) — or (K, T*B, 4, 2) when ``perdir`` — scaled by
    8*B*Tdelta like the reference (analysis_torch.py:173-179), and llr
    (Ts, K).  Chunks run under ``lax.map``; jit once per shape.

    ``optimized`` (static, default) selects the formulation-optimized
    chunk path: scatter-free Hessian, the adjoint 4-RHS Dsolutions ->
    Dresiduals chain, and chunk-loop-invariant operands (split-real
    block forms, Jones gathers, the shared lhs and its per-chunk time
    sum) hoisted out of the ``lax.map`` into one fused pass each.
    ``optimized=False`` is the retained oracle chain — same results to
    float round-off (tested), O(10x) slower at the N=62 episode scale.

    ``block_baselines`` (static, optimized chain only) > 0 runs the
    blocked Hessian core — at N >= 256 the unblocked per-chunk einsum
    temporaries are the memory wall; ``precision`` (static,
    cal/precision.py) selects the mixed bf16 policy for the colmeans
    contraction (documented tolerance; solve/Hessian pinned f32);
    ``use_pallas`` (static, default True) lets the blocked tier promote
    to the tiled Mosaic Hessian on TPU — sharded callers (GSPMD
    programs, where pallas_call has no partitioning rule) pass False.
    """
    B = n_stations * (n_stations - 1) // 2
    T = C.shape[1] // B
    Td = T // n_chunks
    K = C.shape[0]

    if optimized:
        from smartcal_tpu.cal import creal  # local: kernels owns the math

        R3 = R.reshape(n_chunks, Td, B, 2, 2, 2)
        C5 = jnp.moveaxis(jnp.swapaxes(
            C.reshape(K, n_chunks, Td, B, 2, 2, 2), -3, -2), 1, 0)
        p_idx, q_idx = kernels.baseline_indices(n_stations)
        J4 = J.reshape(n_chunks, K, n_stations, 2, 2, 2)
        Jp, Jq = J4[:, :, p_idx], J4[:, :, q_idx]   # (Ts, K, B, 2, 2, 2)
        Csum = jnp.sum(C5, axis=2)                  # (Ts, K, B, 2, 2, 2)
        lhs = creal.einsum("skbuv,skbwv->skbuw", Jq, creal.conj(Csum))

        def one(args):
            r3, c5, jp, jq, lh = args
            return _chunk_influence_opt(r3, c5, jp, jq, lh, hadd,
                                        n_stations, fullpol, perdir,
                                        block_baselines=block_baselines,
                                        precision=precision,
                                        use_pallas=use_pallas)

        vis_b, llr = lax.map(one, (R3, C5, Jp, Jq, lhs))
    else:
        R4 = R.reshape(n_chunks, 2 * B * Td, 2, 2)
        C4 = jnp.moveaxis(C.reshape(K, n_chunks, B * Td, 4, 2), 1, 0)

        def one(args):
            r, c, j = args
            return _chunk_influence(r, c, j, hadd, n_stations, fullpol,
                                    perdir)

        vis_b, llr = lax.map(one, (R4, C4, J))
    scale = 8.0 * B * Td
    if perdir:
        # (Ts, K, B, 4, 2) -> (K, Ts*Td*B, 4, 2) replicating over Td slots
        v = jnp.repeat(vis_b[:, :, None, :, :, :], Td, axis=2)
        vis = jnp.moveaxis(v, 0, 1).reshape(K, T * B, 4, 2) * scale
    else:
        v = jnp.repeat(vis_b[:, None, :, :, :], Td, axis=1)
        vis = v.reshape(T * B, 4, 2) * scale
    return InfluenceResult(vis=vis, llr=llr)


def _chunk_influence_bshard(r3l, c5l, jpl, jql, lhs_l, p_idx_l, q_idx_l,
                            b_offset, hadd, n_stations, b_total, fullpol,
                            perdir, axis_name, precision):
    """One calibration interval with the BASELINE axis sharded over
    ``axis_name`` — every operand is this shard's local baseline slice.
    Collectives happen only at the per-direction reductions: ONE psum of
    the assembled partial Hessian and ONE psum of the adjoint chain's
    per-station G sum (plus the scalar LLR norms); the returned column
    means cover the local baselines."""
    Td = c5l.shape[1]
    K = c5l.shape[0]
    off_l, dsum_l = kernels._hessian_block_sums(
        r3l, c5l, jpl, jql, p_idx_l, q_idx_l, n_stations)
    # place the local off-diagonal blocks at their global slots; the
    # assembled partials live on disjoint (p, q) slots across shards, so
    # the psum IS the global Hessian
    off_tab = jnp.zeros((K, b_total, 4, 4, 2), off_l.dtype)
    off_tab = lax.dynamic_update_slice(off_tab, off_l,
                                       (0, b_offset, 0, 0, 0))
    H = kernels._hessian_assemble(off_tab, dsum_l, n_stations, b_total,
                                  Td)
    H = lax.psum(H, axis_name)
    N4 = H.shape[1]
    H = H.at[:, jnp.arange(N4), jnp.arange(N4), 0].add(hadd[:, None])
    dt = prec.contraction_dtype("colmeans_contract", precision)
    pol_means = kernels._colmeans_adjoint_bshard_sr(
        lhs_l, H, p_idx_l, n_stations, Td, b_total, addself=False,
        perdir=perdir, axis_name=axis_name,
        contract_dtype=None if dt == prec.F32 else dt)
    return _chunk_post(pol_means, fullpol), \
        kernels._llr_bshard_sr(r3l, c5l, jpl, jql, axis_name)


def influence_visibilities_blocal(R3, C5, J, p_idx_l, q_idx_l, hadd,
                                  n_stations, b_total,
                                  fullpol=False, perdir=False,
                                  axis_name="bp", precision="f32"):  # graftlint: disable=mesh-axis-literal -- cal layers below parallel (importing the registry would cycle through parallel.__init__); value matches mesh.AXIS_BASELINE, callers pass the constant
    """Shard-LOCAL body of the baseline-sharded influence engine (called
    inside ``shard_map`` by parallel/sharded_cal.influence_baseline_
    sharded; per-shard shapes).

    R3 (Ts, Td, Bl, 2, 2, 2); C5 (Ts, K, Td, Bl, 2, 2, 2); J (Ts, K,
    2N, 2, 2) replicated; p_idx_l/q_idx_l (Bl,) this shard's station
    indices.  Returns (vis (T, Bl, 4, 2) — (K, T, Bl, 4, 2) when
    ``perdir`` — and llr (Ts, K) replicated); the caller's out_specs
    concatenate the baseline axis back into global time-major order."""
    from smartcal_tpu.cal import creal  # local: kernels owns the math

    Ts, Td = R3.shape[0], R3.shape[1]
    Bl = R3.shape[2]
    K = C5.shape[1]
    b_offset = lax.axis_index(axis_name) * Bl

    J4 = J.reshape(Ts, K, n_stations, 2, 2, 2)
    Jp, Jq = J4[:, :, p_idx_l], J4[:, :, q_idx_l]   # (Ts, K, Bl, 2, 2, 2)
    Csum = jnp.sum(C5, axis=2)                      # (Ts, K, Bl, 2, 2, 2)
    lhs = creal.einsum("skbuv,skbwv->skbuw", Jq, creal.conj(Csum))

    def one(args):
        r3, c5, jp, jq, lh = args
        return _chunk_influence_bshard(
            r3, c5, jp, jq, lh, p_idx_l, q_idx_l, b_offset, hadd,
            n_stations, b_total, fullpol, perdir, axis_name, precision)

    vis_b, llr = lax.map(one, (R3, C5, Jp, Jq, lhs))
    scale = 8.0 * b_total * Td
    if perdir:
        # (Ts, K, Bl, 4, 2) -> (K, Ts*Td, Bl, 4, 2) replicated over Td
        v = jnp.repeat(vis_b[:, :, None, :, :, :], Td, axis=2)
        vis = jnp.moveaxis(v, 0, 1).reshape(K, Ts * Td, Bl, 4, 2) * scale
    else:
        v = jnp.repeat(vis_b[:, None, :, :, :], Td, axis=1)
        vis = v.reshape(Ts * Td, Bl, 4, 2) * scale
    return InfluenceResult(vis=vis, llr=llr)


@partial(jax.jit, static_argnames=("n_stations", "n_chunks", "npix",
                                   "use_pallas", "optimized",
                                   "block_baselines", "imager_block_r",
                                   "precision"))
def influence_images_multi(residual, C, J, hadd_all, freqs, uvw, cell,
                           n_stations, n_chunks, npix, use_pallas=True,
                           optimized=True, block_baselines=0,
                           imager_block_r=0, precision="f32"):
    """Per-sub-band Stokes-I influence dirty images in ONE device dispatch.

    The envs' host loop over sub-bands (residual_to_kernel ->
    influence_visibilities -> dirty image, once per frequency) costs O(Nf)
    dispatches with a host sync between each; here the frequency axis is a
    ``lax.map`` axis inside one jit (lax.map, not vmap: the body stays
    unbatched so the Pallas imager — which has no batching rule — remains
    usable per lane).

    residual (Nf, T, B, 2, 2, 2) solver residuals; C (Nf, K, T*B, 4, 2);
    J (Nf, Ts, K, 2N, 2, 2); hadd_all (Nf, K) per-band consensus scalars
    (:func:`consensus_hadd_all`); freqs (Nf,); uvw (T*B, 3) meters; cell
    static pixel size.  Returns (Nf, npix, npix).

    ``optimized`` (static, default) runs the formulation-optimized chain:
    the optimized :func:`influence_visibilities` kernels, the kernel-
    convention residual reshape hoisted out of the frequency loop, and
    the rank-factored DFT imager (``imager.dirty_image_factored_sr`` —
    matmul-only, so it is also the path used inside sharded programs).
    ``optimized=False`` keeps the oracle chain, where ``use_pallas=False``
    forces the XLA imager (required inside GSPMD/shard_map programs).

    SKA-tier statics (optimized chain only): ``block_baselines`` > 0
    runs the blocked Hessian core; ``imager_block_r`` > 0 swaps in the
    blocked factored imager (``dirty_image_factored_blocked_sr``, the
    npix >= 1024 tier where the (npix, R) planes stop being small);
    ``precision`` selects the bf16 policy rows (cal/precision.py).
    """
    from smartcal_tpu.cal import imager, solver  # lazy: solver is a consumer

    if optimized:
        # frequency-loop hoist: ONE reshape to kernel-convention rows for
        # all sub-bands (the oracle path re-runs residual_to_kernel per
        # lane inside the map)
        Nf, T, B = residual.shape[0], residual.shape[1], residual.shape[2]
        Rk_all = residual.reshape(Nf, 2 * T * B, 2, 2)

        def one(args):
            rk, c, j, hadd, f = args
            inf = influence_visibilities(rk, c, j, hadd, n_stations,
                                         n_chunks, optimized=True,
                                         block_baselines=block_baselines,
                                         precision=precision,
                                         use_pallas=use_pallas)
            ivis = stokes_i_influence(inf.vis)
            if imager_block_r:
                # use_pallas doubles as the GSPMD guard here, exactly as
                # on the oracle chain: sharded callers pass False
                return imager.dirty_image_factored_large_sr(
                    uvw, ivis, f, cell, npix=npix,
                    block_r=imager_block_r, precision=precision,
                    allow_pallas=use_pallas)
            return imager.dirty_image_factored_sr(uvw, ivis, f, cell,
                                                  npix=npix,
                                                  precision=precision)

        return lax.map(one, (Rk_all, C, J, hadd_all, jnp.asarray(freqs)))

    def one(args):
        resid, c, j, hadd, f = args
        Rk = solver.residual_to_kernel(resid)
        inf = influence_visibilities(Rk, c, j, hadd, n_stations, n_chunks,
                                     optimized=False)
        ivis = stokes_i_influence(inf.vis)
        if use_pallas:
            return imager.dirty_image_sr(uvw, ivis, f, cell, npix=npix)
        return imager.dirty_image_sr_xla(uvw, ivis, f, cell, npix=npix)

    return lax.map(one, (residual, C, J, hadd_all, jnp.asarray(freqs)))


@partial(jax.jit, static_argnames=("n_stations", "n_chunks", "npix",
                                   "block_baselines", "imager_block_r",
                                   "precision"))
def influence_image_single_sr(residual_f, C_f, J_f, hadd_f, freq, uvw,
                              cell, n_stations, n_chunks, npix,
                              block_baselines=0, imager_block_r=0,
                              precision="f32"):
    """ONE sub-band's influence dirty image with the optimized kernels —
    the bounded per-dispatch unit of the host-segmented influence route
    (envs/radio.RadioBackend): at the N=62 episode scale the fused
    all-band program runs minutes on a chip (device-watchdog territory,
    same story as the segmented ADMM driver), while this program is
    1/Nf-th the size and the host loop double-buffers it — band f+1's
    dispatch is enqueued while band f executes.  The SKA-tier statics
    (``block_baselines``/``imager_block_r``/``precision``) mirror
    :func:`influence_images_multi` — this is the route big-N episodes
    take on one device, so the blocked kernels must be reachable here."""
    from smartcal_tpu.cal import imager, solver

    Rk = solver.residual_to_kernel(residual_f)
    inf = influence_visibilities(Rk, C_f, J_f, hadd_f, n_stations,
                                 n_chunks, optimized=True,
                                 block_baselines=block_baselines,
                                 precision=precision)
    ivis = stokes_i_influence(inf.vis)
    if imager_block_r:
        # single-band host-segmented unit — never inside a GSPMD
        # program, so the TPU dispatch may pick the Pallas tile kernel
        return imager.dirty_image_factored_large_sr(
            uvw, ivis, freq, cell, npix=npix, block_r=imager_block_r,
            precision=precision)
    return imager.dirty_image_factored_sr(uvw, ivis, freq, cell,
                                          npix=npix, precision=precision)


class PerdirSummary(NamedTuple):
    """Reference analysis_uvw_perdir return (influence_tools.py:346-358)."""

    j_norm: jnp.ndarray     # (K,)
    c_norm: jnp.ndarray     # (K,)
    inf_mean: jnp.ndarray   # (K,) |mean XX + mean YY|
    llr_mean: jnp.ndarray   # (K,)


def perdir_summary(vis_k, llr, C, J) -> PerdirSummary:
    """Per-direction scalars from perdir influence visibilities
    (K, T*B, 4, 2) + llr (Ts, K) + C (K, T*B, 4, 2) + J (Ts, K, 2N, 2, 2)."""
    mean_xx = jnp.mean(vis_k[:, :, 0, :], axis=1)
    mean_yy = jnp.mean(vis_k[:, :, 3, :], axis=1)
    s = mean_xx + mean_yy
    inf_mean = jnp.sqrt(s[:, 0] ** 2 + s[:, 1] ** 2)
    j_norm = jnp.sqrt(jnp.sum(J * J, axis=(0, 2, 3, 4)))
    c_norm = jnp.sqrt(jnp.sum(C * C, axis=(1, 2, 3)))
    return PerdirSummary(j_norm=j_norm, c_norm=c_norm, inf_mean=inf_mean,
                         llr_mean=jnp.mean(llr, axis=0))


def stokes_i_influence(vis):
    """(..., 4, 2) influence visibilities -> (..., 2) Stokes I, the quantity
    imaged into influenceI.fits (doinfluence.sh -> excon Stokes I)."""
    return 0.5 * (vis[..., 0, :] + vis[..., 3, :])
