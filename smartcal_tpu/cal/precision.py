"""bf16/f32 mixed-precision policy for the physics kernels.

SKA-scale episodes (N>=256 stations, B~N^2 baselines, npix>=1024) are
bandwidth-bound on the big contractions; bf16 operands halve the HBM
traffic and double the MXU peak on every validated TPU generation.  But
the calibration chain is NOT uniformly bf16-safe: the ADMM/L-BFGS solve
and the 4Nx4N per-direction Hessian factorizations carry conditioning
constants (EPS_SINGULAR = 1e-12, the quartic line-search cancellation
fix of PR 1) that sit far below bf16's ~3e-3 relative resolution, while
the post-solve LINEAR contractions (the adjoint column-means gather, the
DFT imager matmuls) degrade gracefully — an O(eps_bf16) relative error
on quantities the envs only consume through image statistics.

So precision is a PER-KERNEL policy, not a global switch, and the policy
is decided by the retained parity oracles, not by assumption: every
kernel listed bf16-capable below has a tier-1 test measuring it against
its f32/XLA oracle within the documented tolerance, and every pinned
kernel has a bit-exactness test proving ``precision="bf16"`` does not
touch it (tests/test_nscale_kernels.py).  The measured outcomes that set
this table:

* ``imager_matmul`` — the factored DFT image is a mean over R>=1e4
  visibilities; bf16 operand rounding is zero-mean and the accumulation
  stays f32 (``preferred_element_type``), so image parity holds to ~1e-2
  relative of the image DYNAMIC RANGE (tested) and sigma(img), the env
  observation, to ~1e-2 relative.
* ``colmeans_contract`` — the final Yr x Lr gather-einsum of the adjoint
  influence chain is linear in both operands, downstream of the pinned
  f32 solve; per-element relative error is O(3e-3) (tested vs the f32
  chain).
* ``hessian`` / ``solve_4n`` / ``admm`` — PINNED f32.  Measured: a bf16
  Hessian perturbs the (Dgrad + 1e-12 I)^{-1} factorization at the
  percent level and the ADMM consensus path amplifies it across
  iterations; sigma_res parity vs the host-loop oracle fails the 1e-3
  band the solver tests hold today.  These stay f32 under every policy.

``precision`` is python-STATIC everywhere (same contract as
``optimized=``/``fused=``; enforced by graftlint's traced-static-flag
rule): each value selects a trace, so it must be a host string decided
before tracing.

This module is the ONE place dtype literals are chosen for the policied
kernel modules (cal/imager.py, cal/influence.py, cal/kernels.py,
ops/pallas_imager.py) — graftlint's ``dtype-discipline`` rule flags bare
``jnp.float32``/``jnp.float64`` literals there, so pinned sites either
route through these helpers or carry a ``# graftlint: disable=`` with
the pinning reason.
"""

from __future__ import annotations

import jax.numpy as jnp

#: valid values of the static ``precision=`` argument
POLICIES = ("f32", "bf16")

#: the f32 dtype object the policied modules use for pinned sites
#: (index/coordinate arrays, accumulators, solve operands)
F32 = jnp.float32

#: per-kernel dtype class under the mixed ("bf16") policy; "f32" rows
#: are pinned — the policy never downgrades them (see module docstring
#: for the measured reasons)
KERNEL_DTYPES = {
    "imager_matmul": "bf16",
    "colmeans_contract": "bf16",
    "hessian": "f32",
    "solve_4n": "f32",
    "admm": "f32",
}


def check(precision: str) -> str:
    """Validate a ``precision=`` value (static; raises on unknowns so a
    typo fails at the call site, not as a silent f32 run)."""
    if precision not in POLICIES:
        raise ValueError(
            f"precision={precision!r}: expected one of {POLICIES}")
    return precision


def contraction_dtype(kernel: str, precision: str = "f32"):
    """The OPERAND dtype for ``kernel``'s big contraction under
    ``precision``.  Accumulation stays f32 at every call site
    (``preferred_element_type=F32``); only the operand storage narrows.
    Unknown kernel names are an error — a new kernel must take an
    explicit policy row, not inherit one by accident."""
    check(precision)
    pinned = KERNEL_DTYPES[kernel]
    if precision == "bf16" and pinned == "bf16":
        return jnp.bfloat16
    return F32


def dtype_name(dtype) -> str:
    """Short name for telemetry tags ("bf16"/"f32")."""
    return "bf16" if dtype == jnp.bfloat16 else "f32"
