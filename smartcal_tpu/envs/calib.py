"""CalibEnv: RL environment for tuning per-direction ADMM regularization.

Parity target: ``calibration/calibenv.py`` — action = 2M values in [-1, 1]
(M spectral + M spatial rho), affine-mapped to [LOW, HIGH] with a -0.1
penalty per out-of-range clip (:121-138); observation = {128x128 influence
image x 1e-3, (M+1)x7 sky table x 1e-3} (:164-166); reward =
sigma_data_img / sigma_res_img + 1e-4/(sigma_inf + EPS) + penalty (:170);
reset draws K in [2, M] clusters and re-simulates (:177-204); hint = the
analytic flux-proportional rho with spatial = 5% of spectral (:220-225).

The external dosimul/docal/doinfluence shell pipeline is replaced by the
in-framework backend (envs/radio.py); directions are padded to M so one
compiled solver serves every K.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from smartcal_tpu import obs
from smartcal_tpu.envs import radio

LOW, HIGH = 0.01, 1000.0        # calibenv.py:21-22
INF_SCALE = 1e-3                # calibenv.py:25
META_SCALE = 1e-3
EPS = 0.01


def _to_unit(rho):
    """rho -> [-1, 1] action coordinates (calibenv.py:160-162)."""
    return (rho - (HIGH + LOW) / 2) * (2 / (HIGH - LOW))


class CalibEnv:
    """Gym-style env (reset/step), dict observations {'img', 'sky'}.

    ``prefetch=True`` double-buffers episode construction: after each
    reset, the NEXT episode's simulation (host draws + device dispatches)
    is scheduled on the backend's worker thread, so it overlaps this
    episode's calibrate/influence work (the env-side half of the
    backend's pipelined episode path).  Deterministic — the upcoming
    reset key is a pure function of the seed stream.

    Sweep variance-reduction options (both default OFF — the reference-
    parity reward is unchanged unless a protocol asks for them):

    ``baseline_reward=True`` subtracts a per-episode baseline — the
    reward of the episode's own reset-time calibration (the model/hint
    rho the env starts from) — from every step reward, the demixing
    env's ``reward0`` pattern (demixingenv.py:338-355).  Episode-to-
    episode sky draws dominate the raw reward's variance; differencing
    against the same episode's own baseline removes that component, so
    paired hint/no-hint sweeps need far fewer seeds to power a verdict.

    ``fixed_K=k`` pins the per-episode direction count instead of the
    reference's uniform draw in [2, M] (calibenv.py:177-204) — the other
    dominant reward-variance source.  The K draw still happens (so the
    episode RNG stream, and thus the simulated skies, stay identical to
    a non-fixed run of the same seed) and is then overridden.
    """

    def __init__(self, M=5, provide_hint=False, backend: Optional[
            radio.RadioBackend] = None, seed=0, prefetch=False,
            fixed_K: Optional[int] = None, baseline_reward=False):
        self.M = M
        self.K = 0
        self.provide_hint = provide_hint
        self.hint = None
        self.backend = backend or radio.RadioBackend()
        self.prefetch = prefetch
        if fixed_K is not None and not 2 <= fixed_K <= M:
            raise ValueError(f"fixed_K={fixed_K} outside [2, M={M}]")
        self.fixed_K = fixed_K
        self.baseline_reward = baseline_reward
        self._reward0 = 0.0
        self._pf_tag = None
        self._key = jax.random.PRNGKey(seed)
        self.rho_spectral = np.ones(M, np.float32)
        self.rho_spatial = np.ones(M, np.float32)
        self.ep = None
        self.mdl = None
        self.sky = None
        self._sigma_data_img = 1.0

    def _next_key(self):
        self._key, k = jax.random.split(self._key)
        return k

    @property
    def n_actions(self):
        return 2 * self.M

    def _run_calibration(self):
        mask = np.zeros(self.M, np.float32)
        mask[:self.K] = 1.0
        rho = np.ones(self.M, np.float32)
        rho[:self.K] = self.rho_spectral[:self.K]
        res = self.backend.calibrate(self.ep, rho, mask=mask)
        alpha = np.ones(self.M, np.float32) * 0.0
        alpha[:self.K] = self.rho_spatial[:self.K]
        img = self.backend.influence_image(self.ep, res, rho, alpha)
        return res, np.asarray(img)

    def _observation(self, img):
        self.sky[:self.K, 5] = _to_unit(self.rho_spectral[:self.K])
        self.sky[:self.K, 6] = _to_unit(self.rho_spatial[:self.K])
        return {"img": img * INF_SCALE, "sky": self.sky * META_SCALE}

    def step(self, action):
        action = np.asarray(action, np.float32).squeeze()
        assert action.shape == (2 * self.M,)
        rho = action * (HIGH - LOW) / 2 + (HIGH + LOW) / 2
        self.rho_spectral[:self.K] = rho[:self.K]
        self.rho_spatial[:self.K] = rho[self.M:self.M + self.K]
        penalty = 0.0
        for arr in (self.rho_spectral, self.rho_spatial):
            for ci in range(self.K):
                if arr[ci] < LOW:
                    arr[ci] = LOW
                    penalty += -0.1
                if arr[ci] > HIGH:
                    arr[ci] = HIGH
                    penalty += -0.1

        with obs.span("episode_step", env="calib"):
            res, img = self._run_calibration()
            with obs.span("reward"):
                sigma1 = float(np.std(np.asarray(
                    self.backend.residual_image(self.ep, res))))
                reward = (self._sigma_data_img / max(sigma1, 1e-12)
                          + 1e-4 / (float(img.std()) + EPS) + penalty
                          - self._reward0)
        observation = self._observation(img)
        done = False
        info = {"sigma_res": float(res.sigma_res)}
        if self.provide_hint:
            return observation, reward, done, self.hint, info
        return observation, reward, done, info

    def _build_episode(self, key):
        rng = radio.observation.host_rng(key, salt=21)
        # the draw ALWAYS happens so fixed_K changes only K, never the
        # downstream RNG stream (same-seed skies stay comparable across
        # the fixed/unfixed sweep arms)
        K = int(rng.integers(2, self.M + 1))
        if self.fixed_K is not None:
            K = self.fixed_K
        ep, mdl = self.backend.new_calib_episode(key, K, self.M)
        return K, ep, mdl

    def _prefetch_tag(self, key):
        # namespaced per env INSTANCE: two envs sharing a backend (and
        # possibly a seed stream) must never collide in the registry
        return (f"{type(self).__name__}-{id(self)}-"
                + np.asarray(key).tobytes().hex())

    def reset(self):
        with obs.span("episode_reset", env="calib"):
            return self._reset()

    def _reset(self):
        key = self._next_key()
        got = (self.backend.take_prefetched(self._prefetch_tag(key))
               if self.prefetch else None)
        self.K, self.ep, self.mdl = got or self._build_episode(key)
        if self.prefetch:
            # the key the NEXT reset will draw (split is deterministic):
            # build that episode on the worker while this one calibrates
            nxt = jax.random.split(self._key)[1]
            self._pf_tag = self._prefetch_tag(nxt)
            self.backend.prefetch_episode(
                self._pf_tag, lambda k=nxt: self._build_episode(k))
        self.rho_spectral = np.ones(self.M, np.float32)
        self.rho_spatial = np.ones(self.M, np.float32)
        self.rho_spectral[:self.K] = self.mdl.rho
        self.rho_spatial[:self.K] = self.mdl.rho_spatial

        # sky table (M+1, 7): K rows [id, l, m, sI, sP, ., .], final row
        # [ra0, dec0, K, f_low_GHz, f_high_GHz] (calibenv.py:198-204)
        freqs = np.asarray(self.ep.obs.freqs)
        self.sky = np.zeros((self.M + 1, 7), np.float32)
        self.sky[:self.K, :5] = self.mdl.sky_table
        self.sky[-1, :5] = [self.ep.obs.ra0, self.ep.obs.dec0, self.K,
                            freqs[0] / 1e9, freqs[-1] / 1e9]

        res, img = self._run_calibration()
        self._sigma_data_img = float(np.std(np.asarray(
            self.backend.data_image(self.ep))))
        self._reward0 = 0.0
        if self.baseline_reward:
            # per-episode baseline: the step-reward formula (sans clip
            # penalty) evaluated on this episode's own reset calibration
            # — the demixing env's reward0 pattern
            sigma1 = float(np.std(np.asarray(
                self.backend.residual_image(self.ep, res))))
            self._reward0 = (self._sigma_data_img / max(sigma1, 1e-12)
                             + 1e-4 / (float(img.std()) + EPS))
        if self.provide_hint:
            self.hint = np.zeros(2 * self.M, np.float32)
            self.hint[:self.K] = _to_unit(self.rho_spectral[:self.K])
            self.hint[self.M:self.M + self.K] = _to_unit(
                0.05 * self.rho_spectral[:self.K])
        return self._observation(img)

    def render(self, mode="human"):
        obs.echo(f"{self.rho_spectral} {self.rho_spatial}", event="render")

    def close(self):
        if self._pf_tag is not None:
            self.backend.discard_prefetched(self._pf_tag)
            self._pf_tag = None


class BatchedCalibEnv:
    """``n_envs`` CalibEnv lanes advanced as ONE batched program.

    Lane ``i`` reproduces ``CalibEnv(M, seed=seed + i)`` exactly at the
    episode level: each lane owns an independent key stream (the same
    ``split`` chain a sequential env walks), episode construction stays
    host-side per lane, and everything downstream — the masked ADMM
    solve, the influence chain, the reward images — runs as one vmapped
    (or lane-sharded, on a mesh) program over the lane axis
    (``RadioBackend.calibrate_batched`` and friends).  ``reset``/``step``
    take and return stacked arrays: actions (E, 2M) in, observations
    {'img' (E, npix, npix), 'sky' (E, M+1, 7)}, rewards (E,), dones (E,)
    out.

    Per-lane episode boundaries are MASKED RESETS (``reset_lanes``):
    a done lane's fresh episode splices into the batch through a donated
    per-lane update (static shapes — never a recompile), while live
    lanes keep their state and observation.

    ``fused=False`` is the retained parity oracle (static flag): the
    same lanes route one by one through the sequential
    ``RadioBackend.calibrate``/``influence_image`` path and the results
    are stacked — the oracle the batched program is certified against
    (tests/test_batched_radio.py, tools/certify_batched.py --mode calib).
    """

    def __init__(self, M=5, n_envs=4, provide_hint=False,
                 backend: Optional[radio.RadioBackend] = None, seed=0,
                 fixed_K: Optional[int] = None, baseline_reward=False,
                 fused=True):
        self.M = M
        self.n_envs = int(n_envs)
        self.provide_hint = provide_hint
        self.backend = backend or radio.RadioBackend()
        if fixed_K is not None and not 2 <= fixed_K <= M:
            raise ValueError(f"fixed_K={fixed_K} outside [2, M={M}]")
        self.fixed_K = fixed_K
        self.baseline_reward = baseline_reward
        self.fused = fused
        E = self.n_envs
        # per-lane key streams: lane i walks CalibEnv(seed=seed+i)'s chain
        self._keys = [jax.random.PRNGKey(seed + i) for i in range(E)]
        self.K = np.zeros(E, np.int32)
        self.rho_spectral = np.ones((E, M), np.float32)
        self.rho_spatial = np.ones((E, M), np.float32)
        self.sky = np.zeros((E, M + 1, 7), np.float32)
        self.hint = None
        self._sigma_data_img = np.ones(E, np.float32)
        self._reward0 = np.zeros(E, np.float32)
        # per-lane counters (checkpointed: runtime --resume bit-parity)
        self.lane_episode = np.zeros(E, np.int64)
        self.lane_step = np.zeros(E, np.int64)
        self.eps = [None] * E
        self.mdls = [None] * E
        self.bep = None
        self._last_obs = None

    @property
    def n_actions(self):
        return 2 * self.M

    def _next_lane_key(self, i):
        self._keys[i], k = jax.random.split(self._keys[i])
        return k

    def _build_episode(self, key):
        rng = radio.observation.host_rng(key, salt=21)
        K = int(rng.integers(2, self.M + 1))      # draw ALWAYS happens
        if self.fixed_K is not None:
            K = self.fixed_K
        ep, mdl = self.backend.new_calib_episode(key, K, self.M)
        return K, ep, mdl

    # -- batched calibrate + reward inputs -----------------------------------

    def _lane_rho_mask(self):
        E, M = self.n_envs, self.M
        sel = np.arange(M)[None, :] < self.K[:, None]      # (E, M) live dirs
        mask = sel.astype(np.float32)
        rho = np.where(sel, self.rho_spectral, 1.0).astype(np.float32)
        alpha = np.where(sel, self.rho_spatial, 0.0).astype(np.float32)
        return rho, mask, alpha

    def _run_calibration(self):
        rho, mask, alpha = self._lane_rho_mask()
        if self.fused:
            res = self.backend.calibrate_batched(self.bep, rho, mask=mask)
            imgs = np.asarray(self.backend.influence_images_batched(
                self.bep, res, rho, alpha))
            sig_data, sig_res = self.backend.image_sigmas_batched(
                self.bep, res)
            return (res, imgs, np.asarray(sig_data), np.asarray(sig_res),
                    np.asarray(res.sigma_res))
        # sequential parity oracle: per-lane routes, stacked
        imgs, sig_d, sig_r, sig_res = [], [], [], []
        for i in range(self.n_envs):
            r = self.backend.calibrate(self.eps[i], rho[i], mask=mask[i])
            imgs.append(np.asarray(self.backend.influence_image(
                self.eps[i], r, rho[i], alpha[i])))
            sig_d.append(float(np.std(np.asarray(
                self.backend.data_image(self.eps[i])))))
            sig_r.append(float(np.std(np.asarray(
                self.backend.residual_image(self.eps[i], r)))))
            sig_res.append(float(r.sigma_res))
        return (None, np.stack(imgs), np.asarray(sig_d, np.float32),
                np.asarray(sig_r, np.float32),
                np.asarray(sig_res, np.float32))

    def _observation(self, imgs):
        sel = np.arange(self.M)[None, :] < self.K[:, None]
        self.sky[:, :-1, 5] = np.where(sel, _to_unit(self.rho_spectral),
                                       self.sky[:, :-1, 5])
        self.sky[:, :-1, 6] = np.where(sel, _to_unit(self.rho_spatial),
                                       self.sky[:, :-1, 6])
        return {"img": imgs * INF_SCALE, "sky": self.sky * META_SCALE}

    def reset(self):
        """Reset ALL lanes (the start-of-vector-episode form)."""
        return self.reset_lanes(np.ones(self.n_envs, bool))

    def reset_lanes(self, done):
        """Masked reset: rebuild only the lanes where ``done`` is True
        (host construction + donated splice), then run the batched
        reset-time calibration; live lanes keep their current
        observation/baselines."""
        done = np.asarray(done, bool)
        with obs.span("episode_reset", env="calib_batched",
                      lanes=int(done.sum())):
            return self._reset_lanes(done)

    def _reset_lanes(self, done):
        for i in np.where(done)[0]:
            key = self._next_lane_key(i)
            self.K[i], self.eps[i], self.mdls[i] = self._build_episode(key)
            self.lane_episode[i] += 1
            self.lane_step[i] = 0
            mdl = self.mdls[i]
            self.rho_spectral[i] = 1.0
            self.rho_spatial[i] = 1.0
            self.rho_spectral[i, :self.K[i]] = mdl.rho
            self.rho_spatial[i, :self.K[i]] = mdl.rho_spatial
            freqs = np.asarray(self.eps[i].obs.freqs)
            self.sky[i] = 0.0
            self.sky[i, :self.K[i], :5] = mdl.sky_table
            self.sky[i, -1, :5] = [self.eps[i].obs.ra0,
                                   self.eps[i].obs.dec0, self.K[i],
                                   freqs[0] / 1e9, freqs[-1] / 1e9]
            if self.bep is not None:
                self.bep = self.backend.splice_episode(self.bep, int(i),
                                                       self.eps[i])
        if self.bep is None:
            self.bep = self.backend.stack_episodes(self.eps)

        _, imgs, sig_data, sig_res_img, _ = self._run_calibration()
        self._sigma_data_img[done] = sig_data[done]
        self._reward0[done] = 0.0
        if self.baseline_reward:
            r0 = (sig_data / np.maximum(sig_res_img, 1e-12)
                  + 1e-4 / (imgs.std(axis=(1, 2)) + EPS))
            self._reward0[done] = r0[done]
        if self.provide_hint:
            if self.hint is None:
                self.hint = np.zeros((self.n_envs, 2 * self.M), np.float32)
            # only the RESET lanes re-derive their hint (the analytic
            # reset-time rho); live lanes keep the hint of their own
            # episode — their rho_spectral has moved with the steps
            for i in np.where(done)[0]:
                Ki = self.K[i]
                self.hint[i] = 0.0
                self.hint[i, :Ki] = _to_unit(self.rho_spectral[i, :Ki])
                self.hint[i, self.M:self.M + Ki] = _to_unit(
                    0.05 * self.rho_spectral[i, :Ki])
        new_obs = self._observation(imgs)
        if self._last_obs is not None:
            # live lanes keep their pre-reset observation
            keep = ~done
            for k in new_obs:
                new_obs[k][keep] = self._last_obs[k][keep]
        self._last_obs = new_obs
        return new_obs

    def step(self, actions):
        actions = np.asarray(actions, np.float32).reshape(
            self.n_envs, 2 * self.M)
        rho = actions * (HIGH - LOW) / 2 + (HIGH + LOW) / 2
        sel = np.arange(self.M)[None, :] < self.K[:, None]
        self.rho_spectral = np.where(sel, rho[:, :self.M],
                                     self.rho_spectral)
        self.rho_spatial = np.where(sel, rho[:, self.M:],
                                    self.rho_spatial)
        penalty = np.zeros(self.n_envs, np.float32)
        for arr in (self.rho_spectral, self.rho_spatial):
            penalty += -0.1 * np.sum(sel & (arr < LOW), axis=1)
            penalty += -0.1 * np.sum(sel & (arr > HIGH), axis=1)
            np.clip(arr, LOW, HIGH, out=arr)

        with obs.span("episode_step", env="calib_batched",
                      lanes=self.n_envs):
            _, imgs, _, sig_res_img, sigma_res = self._run_calibration()
            rewards = (self._sigma_data_img
                       / np.maximum(sig_res_img, 1e-12)
                       + 1e-4 / (imgs.std(axis=(1, 2)) + EPS) + penalty
                       - self._reward0).astype(np.float32)
        self.lane_step += 1
        observation = self._observation(imgs)
        self._last_obs = observation
        dones = np.zeros(self.n_envs, bool)
        infos = {"sigma_res": sigma_res}
        if self.provide_hint:
            return observation, rewards, dones, self.hint, infos
        return observation, rewards, dones, infos

    # -- checkpoint round-trip (runtime --resume bit-parity) -----------------

    def state_dict(self):
        """Host payload of everything a resumed run needs to continue the
        lane streams bit-continuably: the per-lane key ARRAY and the
        per-lane episode/step counters (episodes themselves are a pure
        function of the keys and are rebuilt by the next reset)."""
        return {
            "kind": "batched_calib_env",
            "keys": np.stack([np.asarray(k) for k in self._keys]),
            "lane_episode": self.lane_episode.copy(),
            "lane_step": self.lane_step.copy(),
        }

    def load_state_dict(self, state):
        keys = np.asarray(state["keys"])
        assert keys.shape[0] == self.n_envs, \
            f"checkpoint has {keys.shape[0]} lanes, env has {self.n_envs}"
        self._keys = [jnp.asarray(k) for k in keys]
        self.lane_episode = np.asarray(state["lane_episode"]).copy()
        self.lane_step = np.asarray(state["lane_step"]).copy()

    def close(self):
        pass
