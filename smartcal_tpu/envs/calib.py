"""CalibEnv: RL environment for tuning per-direction ADMM regularization.

Parity target: ``calibration/calibenv.py`` — action = 2M values in [-1, 1]
(M spectral + M spatial rho), affine-mapped to [LOW, HIGH] with a -0.1
penalty per out-of-range clip (:121-138); observation = {128x128 influence
image x 1e-3, (M+1)x7 sky table x 1e-3} (:164-166); reward =
sigma_data_img / sigma_res_img + 1e-4/(sigma_inf + EPS) + penalty (:170);
reset draws K in [2, M] clusters and re-simulates (:177-204); hint = the
analytic flux-proportional rho with spatial = 5% of spectral (:220-225).

The external dosimul/docal/doinfluence shell pipeline is replaced by the
in-framework backend (envs/radio.py); directions are padded to M so one
compiled solver serves every K.
"""

from typing import Optional

import jax
import numpy as np

from smartcal_tpu import obs
from smartcal_tpu.envs import radio

LOW, HIGH = 0.01, 1000.0        # calibenv.py:21-22
INF_SCALE = 1e-3                # calibenv.py:25
META_SCALE = 1e-3
EPS = 0.01


def _to_unit(rho):
    """rho -> [-1, 1] action coordinates (calibenv.py:160-162)."""
    return (rho - (HIGH + LOW) / 2) * (2 / (HIGH - LOW))


class CalibEnv:
    """Gym-style env (reset/step), dict observations {'img', 'sky'}.

    ``prefetch=True`` double-buffers episode construction: after each
    reset, the NEXT episode's simulation (host draws + device dispatches)
    is scheduled on the backend's worker thread, so it overlaps this
    episode's calibrate/influence work (the env-side half of the
    backend's pipelined episode path).  Deterministic — the upcoming
    reset key is a pure function of the seed stream.

    Sweep variance-reduction options (both default OFF — the reference-
    parity reward is unchanged unless a protocol asks for them):

    ``baseline_reward=True`` subtracts a per-episode baseline — the
    reward of the episode's own reset-time calibration (the model/hint
    rho the env starts from) — from every step reward, the demixing
    env's ``reward0`` pattern (demixingenv.py:338-355).  Episode-to-
    episode sky draws dominate the raw reward's variance; differencing
    against the same episode's own baseline removes that component, so
    paired hint/no-hint sweeps need far fewer seeds to power a verdict.

    ``fixed_K=k`` pins the per-episode direction count instead of the
    reference's uniform draw in [2, M] (calibenv.py:177-204) — the other
    dominant reward-variance source.  The K draw still happens (so the
    episode RNG stream, and thus the simulated skies, stay identical to
    a non-fixed run of the same seed) and is then overridden.
    """

    def __init__(self, M=5, provide_hint=False, backend: Optional[
            radio.RadioBackend] = None, seed=0, prefetch=False,
            fixed_K: Optional[int] = None, baseline_reward=False):
        self.M = M
        self.K = 0
        self.provide_hint = provide_hint
        self.hint = None
        self.backend = backend or radio.RadioBackend()
        self.prefetch = prefetch
        if fixed_K is not None and not 2 <= fixed_K <= M:
            raise ValueError(f"fixed_K={fixed_K} outside [2, M={M}]")
        self.fixed_K = fixed_K
        self.baseline_reward = baseline_reward
        self._reward0 = 0.0
        self._pf_tag = None
        self._key = jax.random.PRNGKey(seed)
        self.rho_spectral = np.ones(M, np.float32)
        self.rho_spatial = np.ones(M, np.float32)
        self.ep = None
        self.mdl = None
        self.sky = None
        self._sigma_data_img = 1.0

    def _next_key(self):
        self._key, k = jax.random.split(self._key)
        return k

    @property
    def n_actions(self):
        return 2 * self.M

    def _run_calibration(self):
        mask = np.zeros(self.M, np.float32)
        mask[:self.K] = 1.0
        rho = np.ones(self.M, np.float32)
        rho[:self.K] = self.rho_spectral[:self.K]
        res = self.backend.calibrate(self.ep, rho, mask=mask)
        alpha = np.ones(self.M, np.float32) * 0.0
        alpha[:self.K] = self.rho_spatial[:self.K]
        img = self.backend.influence_image(self.ep, res, rho, alpha)
        return res, np.asarray(img)

    def _observation(self, img):
        self.sky[:self.K, 5] = _to_unit(self.rho_spectral[:self.K])
        self.sky[:self.K, 6] = _to_unit(self.rho_spatial[:self.K])
        return {"img": img * INF_SCALE, "sky": self.sky * META_SCALE}

    def step(self, action):
        action = np.asarray(action, np.float32).squeeze()
        assert action.shape == (2 * self.M,)
        rho = action * (HIGH - LOW) / 2 + (HIGH + LOW) / 2
        self.rho_spectral[:self.K] = rho[:self.K]
        self.rho_spatial[:self.K] = rho[self.M:self.M + self.K]
        penalty = 0.0
        for arr in (self.rho_spectral, self.rho_spatial):
            for ci in range(self.K):
                if arr[ci] < LOW:
                    arr[ci] = LOW
                    penalty += -0.1
                if arr[ci] > HIGH:
                    arr[ci] = HIGH
                    penalty += -0.1

        with obs.span("episode_step", env="calib"):
            res, img = self._run_calibration()
            with obs.span("reward"):
                sigma1 = float(np.std(np.asarray(
                    self.backend.residual_image(self.ep, res))))
                reward = (self._sigma_data_img / max(sigma1, 1e-12)
                          + 1e-4 / (float(img.std()) + EPS) + penalty
                          - self._reward0)
        observation = self._observation(img)
        done = False
        info = {"sigma_res": float(res.sigma_res)}
        if self.provide_hint:
            return observation, reward, done, self.hint, info
        return observation, reward, done, info

    def _build_episode(self, key):
        rng = radio.observation.host_rng(key, salt=21)
        # the draw ALWAYS happens so fixed_K changes only K, never the
        # downstream RNG stream (same-seed skies stay comparable across
        # the fixed/unfixed sweep arms)
        K = int(rng.integers(2, self.M + 1))
        if self.fixed_K is not None:
            K = self.fixed_K
        ep, mdl = self.backend.new_calib_episode(key, K, self.M)
        return K, ep, mdl

    def _prefetch_tag(self, key):
        # namespaced per env INSTANCE: two envs sharing a backend (and
        # possibly a seed stream) must never collide in the registry
        return (f"{type(self).__name__}-{id(self)}-"
                + np.asarray(key).tobytes().hex())

    def reset(self):
        with obs.span("episode_reset", env="calib"):
            return self._reset()

    def _reset(self):
        key = self._next_key()
        got = (self.backend.take_prefetched(self._prefetch_tag(key))
               if self.prefetch else None)
        self.K, self.ep, self.mdl = got or self._build_episode(key)
        if self.prefetch:
            # the key the NEXT reset will draw (split is deterministic):
            # build that episode on the worker while this one calibrates
            nxt = jax.random.split(self._key)[1]
            self._pf_tag = self._prefetch_tag(nxt)
            self.backend.prefetch_episode(
                self._pf_tag, lambda k=nxt: self._build_episode(k))
        self.rho_spectral = np.ones(self.M, np.float32)
        self.rho_spatial = np.ones(self.M, np.float32)
        self.rho_spectral[:self.K] = self.mdl.rho
        self.rho_spatial[:self.K] = self.mdl.rho_spatial

        # sky table (M+1, 7): K rows [id, l, m, sI, sP, ., .], final row
        # [ra0, dec0, K, f_low_GHz, f_high_GHz] (calibenv.py:198-204)
        freqs = np.asarray(self.ep.obs.freqs)
        self.sky = np.zeros((self.M + 1, 7), np.float32)
        self.sky[:self.K, :5] = self.mdl.sky_table
        self.sky[-1, :5] = [self.ep.obs.ra0, self.ep.obs.dec0, self.K,
                            freqs[0] / 1e9, freqs[-1] / 1e9]

        res, img = self._run_calibration()
        self._sigma_data_img = float(np.std(np.asarray(
            self.backend.data_image(self.ep))))
        self._reward0 = 0.0
        if self.baseline_reward:
            # per-episode baseline: the step-reward formula (sans clip
            # penalty) evaluated on this episode's own reset calibration
            # — the demixing env's reward0 pattern
            sigma1 = float(np.std(np.asarray(
                self.backend.residual_image(self.ep, res))))
            self._reward0 = (self._sigma_data_img / max(sigma1, 1e-12)
                             + 1e-4 / (float(img.std()) + EPS))
        if self.provide_hint:
            self.hint = np.zeros(2 * self.M, np.float32)
            self.hint[:self.K] = _to_unit(self.rho_spectral[:self.K])
            self.hint[self.M:self.M + self.K] = _to_unit(
                0.05 * self.rho_spectral[:self.K])
        return self._observation(img)

    def render(self, mode="human"):
        obs.echo(f"{self.rho_spectral} {self.rho_spatial}", event="render")

    def close(self):
        if self._pf_tag is not None:
            self.backend.discard_prefetched(self._pf_tag)
            self._pf_tag = None
