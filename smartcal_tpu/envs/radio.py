"""Shared episode backend for the radio RL environments.

The reference envs drive an external pipeline per episode/step
(``calibenv.py`` shells dosimul.sh/docal.sh/doinfluence.sh,
``demixingenv.py`` shells mpirun sagecal-mpi + excon): simulate an
observation, calibrate it, compute influence maps, and read noise
statistics back from files.  Here the same contract is served by the
in-framework backend (cal/*): everything below the env API is jit-compiled
JAX on device, and one episode's data lives in device arrays, not an MS on
disk.

Static-shape design (the TPU-first move): instead of rewriting cluster
files per action like the reference, direction selection is a MASK over a
fixed K-direction coherency tensor — unselected directions have their
coherencies zeroed, so one compiled solver serves every subset, and the
2^(K-1) exhaustive hint sweep becomes a single vmap over masks rather than
the reference's 32 sequential MPI launches (demixingenv.py:301-336).

Episode pipeline design (the device-pipelined hot path):
  * construction is VECTORIZED over the frequency axis — coherency
    prediction, shapelet addition, Jones corruption, and noise are each
    ONE device dispatch for all Nf sub-bands (``vectorized=False`` keeps
    the original per-frequency host loop as the parity oracle);
  * with more than one device, ``calibrate`` routes to the
    frequency-sharded consensus solve and ``influence_image`` to the
    sharded influence kernels (parallel/sharded_cal) — the envs get the
    mesh for free through the backend (``shard="auto"``);
  * ``run_pipelined`` overlaps episode t+1's construction with episode
    t's calibrate/influence work on a worker thread (IMPACT-style
    actor/learner overlap, arXiv 1912.00167) — deterministic, since
    every draw is keyed.
"""

import os
import threading
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from smartcal_tpu import obs
# costs imported under its own name: several backend methods take the
# Observation as a parameter named ``obs``, shadowing the package module
from smartcal_tpu.obs import costs as obs_costs
from smartcal_tpu.cal import (coherency, imager, influence, observation,
                              simulate, solver)
# the canonical axis-name registry (ISSUE 17): mesh.py has no package-
# internal imports, so this resolves before any parallel/envs cycle
from smartcal_tpu.parallel.mesh import (AXIS_BASELINE, AXIS_CHUNK,
                                        AXIS_FREQ, AXIS_LANE,
                                        largest_divisor)

# calibration-unit thresholds (see RadioBackend._fused_work): one fused
# XLA program above _WATCHDOG_WORK risks tripping device/tunnel watchdogs
# (measured on the v5e tunnel, ~35 s of chip time); sharding below
# _SHARD_MIN_WORK costs more in collective/dispatch overhead than the
# fan-out returns, so "auto" leaves tiny training configs alone.
_WATCHDOG_WORK = 1e7
_SHARD_MIN_WORK = 1e6

# SKA-tier thresholds (ISSUE 13): above _BLOCK_MIN_B baselines (N=128 ->
# B=8128) the influence chain's per-chunk (K, Td, B)-scale einsum
# temporaries become the memory wall, so the blocked Hessian core and
# (with a mesh) the baseline shard axis take over; npix >= _IMAGER_BLOCK
# _MIN_NPIX swaps the factored imager for its R-blocked twin (the
# (npix, R) planes are ~2.7 GB each at npix=1024 x N=256).  Block sizes
# keep the per-block live set in the tens-of-MB band on every backend.
_BLOCK_MIN_B = 8128
_BLOCK_BASELINES = 2048
_IMAGER_BLOCK_MIN_NPIX = 512
_IMAGER_BLOCK_R = 4096

# donated-carry image accumulator for the host-segmented influence route:
# band f's running sum is donated into band f+1's add, so the per-band
# loop holds ONE image buffer on the device (no-op on CPU, where buffer
# donation is unsupported)
_img_acc = jax.jit(lambda acc, img: acc + img, donate_argnums=(0,))


class Episode(NamedTuple):
    """Device-resident state of one simulated observation."""

    obs: observation.Observation
    V: jnp.ndarray          # (Nf, T, B, 2, 2, 2) observed (corrupted+noise)
    Ccal: jnp.ndarray       # (Nf, K, T*B, 4, 2) calibration-model coherencies
    f0: float
    n_dirs: int
    snr: float


class BatchedEpisode(NamedTuple):
    """B stacked episodes: the per-lane arrays of :class:`Episode` with a
    leading lane axis, the operand form of the batched (vmapped/sharded)
    calibrate -> influence chain.

    Construction stays host-side per lane (the sky draws are variable-
    length numpy), so stacking is the batching boundary: everything
    downstream of ``stack_episodes`` is one keyed, static-shape program
    over the lane axis.  ``V``/``Ccal`` are device arrays (the big
    operands; lane replacement on masked resets goes through a DONATED
    splice so the batch buffer is reused in place on accelerators);
    the small per-lane scalars stay host numpy.
    """

    V: jnp.ndarray          # (E, Nf, T, B, 2, 2, 2)
    Ccal: jnp.ndarray       # (E, Nf, K, T*B, 4, 2)
    freqs: np.ndarray       # (E, Nf) Hz
    f0: np.ndarray          # (E,)
    uvw: np.ndarray         # (E, T*B, 3) meters
    cell: np.ndarray        # (E,) imaging pixel size (rad)
    n_dirs: int             # static K/M (equal across lanes)

    @property
    def n_envs(self) -> int:
        return self.V.shape[0]


# donated per-lane splice for masked resets: lane i's fresh episode
# overwrites its slot of the batched buffer IN PLACE on accelerators
# (donation is a no-op on CPU) — one compiled program per array shape,
# reused for every lane index and reset count (the index is traced), so
# per-lane episode boundaries never recompile the batch.
_lane_splice = jax.jit(lambda full, new, lane: full.at[lane].set(new),
                       donate_argnums=(0,))


class RadioBackend:
    """Hermetic observation + calibration service for the envs.

    n_times = Ts * tdelta total integration slots; every ``tdelta`` slots
    share one solution interval (sagecal -t).

    vectorized : True (default) builds episodes with the one-dispatch
        multi-frequency kernels; False keeps the original per-frequency
        host loop (the parity oracle and the pre-pipeline baseline
        bench.py compares against).
    shard : "auto" | True | False — mesh-aware solve/influence routing.
        "auto" enables the frequency-sharded ADMM + sharded influence
        when more than one device is visible AND the episode is big
        enough to amortize the collectives (_SHARD_MIN_WORK); True
        forces sharding whenever a divisible mesh exists; False never
        shards.  SMARTCAL_SHARD=0/1 overrides.
    precision : "f32" | "bf16" (static) — the cal/precision.py policy
        for the influence/imaging chain; the solve is policy-pinned f32
        either way.  Parity-gated: every bf16-capable kernel is tested
        against its f32 oracle within a documented tolerance.
    block_baselines / imager_block_r : blocked-kernel block sizes
        (None = auto by threshold — blocked Hessian at B >= 8128,
        R-blocked imager at npix >= 512; 0 = force-unblocked).  With a
        mesh and B >= the same threshold, ``influence_image`` routes
        baseline-SHARDED first (the axis that makes SKA-scale episodes
        fit).
    """

    def __init__(self, n_stations=14, n_freqs=3, n_times=20, tdelta=10,
                 n_poly=2, admm_iters=10, lbfgs_iters=8, init_iters=30,
                 polytype=0, npix=128, hint_batch=8, vectorized=True,
                 shard="auto", robust_solver=True, solver_max_retries=2,
                 solver_rho_boost=10.0, precision="f32",
                 block_baselines=None, imager_block_r=None):
        if n_times <= 0 or n_times % tdelta != 0:
            raise ValueError(
                f"n_times={n_times} must be a positive multiple of "
                f"tdelta={tdelta}: every solution interval needs the same "
                "number of slots (vis_to_chunks/coherency_to_chunks reshape "
                "by Ts)")
        self.n_stations = n_stations
        self.n_freqs = n_freqs
        self.n_times = n_times
        self.tdelta = tdelta
        self.n_chunks = n_times // tdelta
        self.n_poly = n_poly
        self.admm_iters = admm_iters
        self.lbfgs_iters = lbfgs_iters
        self.init_iters = init_iters
        self.polytype = polytype
        self.npix = npix
        # hint-sweep vmap width: on accelerators wide lanes win; on CPU
        # vmapped while_loops cost every lane the worst lane's iteration
        # count (and cond becomes select), so hint_batch=1 (sequential
        # lax.map, per-lane early exit) is faster on one core
        self.hint_batch = hint_batch
        self.vectorized = vectorized
        self.shard = shard
        # graceful degradation (runtime PR): non-finite consensus iterates
        # re-solve at boosted rho, then fall back to the host-segmented
        # route, before surfacing SolverDegradedError — one bad episode
        # degrades instead of crashing a batch.  SMARTCAL_ROBUST_SOLVER=0/1
        # overrides the constructor flag.
        self.robust_solver = robust_solver
        self.solver_max_retries = solver_max_retries
        self.solver_rho_boost = solver_rho_boost
        # SKA-tier knobs (python-STATIC — each value selects a trace):
        # precision in {"f32", "bf16"} picks the mixed-precision policy
        # (cal/precision.py; the policy itself pins the solve/Hessian to
        # f32, so "bf16" narrows only the oracle-validated contractions);
        # block_baselines / imager_block_r override the blocked-kernel
        # block sizes (None = auto by the _BLOCK_* / _IMAGER_BLOCK_*
        # thresholds, 0 = force-unblocked).
        from smartcal_tpu.cal import precision as _prec

        self.precision = _prec.check(precision)
        self.block_baselines = block_baselines
        self.imager_block_r = imager_block_r
        self._sweep_fns = {}     # (n_dirs, n_masks, batch) -> jitted sweep
        self._batched_fns = {}   # (kind, shape sig) -> jitted batched prog
        self._meshes = {}        # (size, axis) / (nl, nb) -> cached mesh
        # double-buffer worker (run_pipelined / env prefetch)
        self._prefetch_lock = threading.Lock()
        self._prefetch_ex = None
        self._prefetched = {}

    # -- episode construction ------------------------------------------------

    def _coherencies(self, obs, sky):
        uvw = np.asarray(obs.uvw).reshape(-1, 3)
        if self.vectorized:
            return coherency.predict_coherencies_multi_sr(
                uvw[:, 0], uvw[:, 1], uvw[:, 2], sky, obs.freqs)
        return jnp.stack([
            coherency.predict_coherencies_sr(uvw[:, 0], uvw[:, 1], uvw[:, 2],
                                             sky, f)
            for f in np.asarray(obs.freqs)])

    def _corrupt_and_noise(self, key, obs, Csim, J_extra_dirs, snr,
                           amp, spatial_term, lm_dirs):
        """Predict DATA: corrupt the sim sky with synthetic systematics and
        add noise (roles of sagecal -p sim + addnoise.py)."""
        K_sim = Csim.shape[1]
        n_err = K_sim - J_extra_dirs
        Jerr = simulate.synth_solutions(
            key, n_err, self.n_stations, self.n_chunks, np.asarray(obs.freqs),
            float(np.asarray(obs.freqs).mean()), amp=amp,
            spatial_term=spatial_term, lm_dirs=lm_dirs)
        Jid = simulate.identity_solutions(J_extra_dirs, self.n_stations,
                                          self.n_chunks, self.n_freqs)
        Jsim = np.concatenate([Jerr, Jid], axis=2)
        if self.vectorized:
            # one dispatch for all sub-bands, and the noise scale/add stays
            # on device — no np.asarray(V) host sync mid-construction
            V = solver.simulate_vis_multi_sr(jnp.asarray(Jsim), Csim,
                                             self.n_stations, self.n_chunks)
            # defer=True: this runs inside the simulate/episode spans —
            # the one-time AOT cost analysis must not inflate the very
            # span totals the roofline divides by (flushed between
            # episodes by TrainObs)
            obs_costs.record_stage_cost(
                "simulate", solver.simulate_vis_multi_sr,
                jnp.asarray(Jsim), Csim,
                static_argnames=("n_stations", "Ts"), defer=True,
                n_stations=self.n_stations, Ts=self.n_chunks)
            Vn, _ = simulate.add_noise_device(key, V, snr=snr)
            return Vn
        V = jnp.stack([
            solver.simulate_vis_sr(jnp.asarray(Jsim[f]), Csim[f],
                                   self.n_stations, self.n_chunks)
            for f in range(self.n_freqs)])
        Vn, _ = simulate.add_noise(key, np.asarray(V), snr=snr)
        return jnp.asarray(Vn)

    def _add_shapelet(self, obs, C, coeff, beta, flux):
        """Add a diffuse shapelet component to cluster 0 of a coherency
        tensor (cal/shapelets.py; the role of SAGECal's in-solver shapelet
        prediction for the reference's random diffuse sky)."""
        from smartcal_tpu.cal import shapelets

        uvw = np.asarray(obs.uvw).reshape(-1, 3)
        if self.vectorized:
            add = shapelets.shapelet_coherency_multi_sr(
                coeff, uvw[:, 0], uvw[:, 1], obs.freqs, beta, flux=flux)
        else:
            add = jnp.stack([
                shapelets.shapelet_coherency_sr(coeff, uvw[:, 0], uvw[:, 1],
                                                float(f), beta, flux=flux)
                for f in np.asarray(obs.freqs)])
        return C.at[:, 0].add(add)

    def new_calib_episode(self, key, K, M, diffuse=False):
        """CalibEnv episode: K drawn clusters padded to M directions.
        Returns (episode, models) with Ccal zero-padded to M directions."""
        with obs.span("simulate", kind="calib", K=K):
            return self._new_calib_episode(key, K, M, diffuse)

    def _new_calib_episode(self, key, K, M, diffuse):
        obs = observation.make_observation(
            key, n_stations=self.n_stations, n_freqs=self.n_freqs,
            n_times=self.n_times)
        mdl = simulate.simulate_models(key, K=K, f0=float(
            np.asarray(obs.freqs).mean()), diffuse=diffuse)
        Csim = self._coherencies(obs, mdl.sky_sim)
        if mdl.shapelet is not None:
            Csim = self._add_shapelet(obs, Csim, mdl.shapelet.coeff,
                                      mdl.shapelet.beta, mdl.shapelet.flux)
        V = self._corrupt_and_noise(key, obs, Csim, J_extra_dirs=1, snr=0.05,
                                    amp=1.0, spatial_term=True,
                                    lm_dirs=mdl.lm_dirs)
        Ck = self._coherencies(obs, mdl.sky_cal)
        if mdl.shapelet is not None:
            Ck = self._add_shapelet(obs, Ck, mdl.shapelet.coeff_cal,
                                    mdl.shapelet.beta_cal,
                                    mdl.shapelet.flux)
        pad = M - K
        Ccal = jnp.pad(Ck, ((0, 0), (0, pad), (0, 0), (0, 0), (0, 0)))
        ep = Episode(obs=obs, V=V, Ccal=Ccal, f0=mdl.f0, n_dirs=M, snr=0.05)
        return ep, mdl

    def new_demixing_episode(self, key, K):
        """DemixingEnv episode: K-1 A-team outliers + target."""
        with obs.span("simulate", kind="demix", K=K):
            return self._new_demixing_episode(key, K)

    def _new_demixing_episode(self, key, K):
        rng = observation.host_rng(key, salt=20)
        strategy = int(rng.integers(0, 3))
        ra0, dec0, t0 = observation.find_valid_target(
            key, strategy=1 if strategy == 1 else 0)
        hba = bool(rng.integers(0, 2))
        obs = observation.make_observation(
            key, n_stations=self.n_stations, n_freqs=self.n_freqs,
            n_times=self.n_times, hba=hba, ra0=ra0, dec0=dec0, t0=t0)
        f0 = float(np.asarray(obs.freqs).mean())
        mdl = simulate.simulate_demixing_sky(key, ra0, dec0, t0, f0, K=K)
        Csim = self._coherencies(obs, mdl.sky_sim)
        snr = float(0.05 + rng.random() * 0.45)
        V = self._corrupt_and_noise(key, obs, Csim, J_extra_dirs=1, snr=snr,
                                    amp=0.01, spatial_term=False,
                                    lm_dirs=mdl.lm_dirs)
        Ccal = self._coherencies(obs, mdl.sky_cal)
        ep = Episode(obs=obs, V=V, Ccal=Ccal, f0=f0, n_dirs=K, snr=snr)
        return ep, mdl

    # -- episode pipelining --------------------------------------------------

    def _worker(self):
        from concurrent.futures import ThreadPoolExecutor

        with self._prefetch_lock:
            if self._prefetch_ex is None:
                self._prefetch_ex = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="smartcal-episode")
            return self._prefetch_ex

    def prefetch_episode(self, tag, build):
        """Schedule ``build()`` (an episode constructor) on the backend's
        worker thread, keyed by ``tag``.  JAX dispatch is thread-safe and
        every draw is keyed, so the construction overlaps the caller's
        device work without changing any result.

        Callers sharing one backend must NAMESPACE their tags (the envs
        prefix theirs with the env instance identity): a bare PRNG-key
        tag collides across two envs walking the same seed stream."""
        self._prefetched[tag] = self._worker().submit(build)
        obs.gauge_set("prefetch_pending", len(self._prefetched))

    def take_prefetched(self, tag):
        """Collect a previously prefetched episode (None if absent)."""
        fut = self._prefetched.pop(tag, None)
        if fut is None:
            obs.counter_add("prefetch_miss")
            return None
        ready = fut.done()
        obs.counter_add("prefetch_hit" if ready else "prefetch_stall")
        # the stall wait is the pipeline's exposed construction time —
        # the quantity the double-buffering is supposed to hide
        with obs.span("prefetch_wait", ready=ready):
            return fut.result()

    def discard_prefetched(self, tag):
        """Drop a pending prefetch without consuming it (env close):
        an abandoned future would otherwise pin its episode's device
        buffers for the backend's lifetime."""
        fut = self._prefetched.pop(tag, None)
        if fut is not None:
            fut.cancel()

    def run_pipelined(self, keys, make_episode, process):
        """Double-buffered episode pipeline: yields ``process(ep, mdl)``
        per key while episode t+1's ``make_episode(key)`` (host RNG draws
        + simulation dispatches) runs on the worker thread alongside
        episode t's calibrate/influence device work.

        The serial loop pays (host sim setup + device solve) per episode;
        here the host setup hides behind the previous episode's solve —
        the IMPACT overlap (arXiv 1912.00167) at episode granularity.
        Deterministic: outputs are a pure function of the keys.
        """
        keys = list(keys)
        if not keys:
            return
        ex = self._worker()
        fut = ex.submit(make_episode, keys[0])
        for i in range(len(keys)):
            with obs.span("prefetch_wait", pipelined=True):
                ep, mdl = fut.result()
            if i + 1 < len(keys):
                fut = ex.submit(make_episode, keys[i + 1])
            yield process(ep, mdl)

    # -- calibration + influence --------------------------------------------

    def _solver_cfg(self, K):
        return solver.SolverConfig(
            n_stations=self.n_stations, n_dirs=K, n_poly=self.n_poly,
            admm_iters=self.admm_iters, lbfgs_iters=self.lbfgs_iters,
            init_iters=self.init_iters, polytype=self.polytype)

    def _fused_work(self, admm_iters=None):
        """Calibration units of one fused solve: total L-BFGS iterations x
        per-iteration work, with the per-call ADMM iteration override (the
        demixing action's maxiter) counted, not the constructor default."""
        admm = self.admm_iters if admm_iters is None else int(admm_iters)
        total_iters = self.init_iters + admm * self.lbfgs_iters
        return total_iters * (self.n_stations ** 2) * self.n_freqs \
            * self.n_times

    def _shard_size(self, n_items, work):
        """Mesh axis size for sharding ``n_items`` (0 = don't shard):
        the largest divisor of n_items that fits the device count,
        subject to the shard mode (see class docstring)."""
        mode = self.shard
        override = os.environ.get("SMARTCAL_SHARD", "").strip()
        if override in ("0", "1"):
            mode = override == "1"
        if mode is False or mode is None:
            return 0
        if mode == "auto" and work < _SHARD_MIN_WORK:
            return 0
        try:
            ndev = jax.device_count()
        except RuntimeError:
            return 0
        if ndev < 2:
            return 0
        for size in range(min(ndev, n_items), 1, -1):
            if n_items % size == 0:
                return size
        return 0

    def _mesh(self, size, axis=AXIS_FREQ):
        """Cached 1-D mesh whose single axis carries the registry name of
        the ROLE it plays (frequency / chunk / baseline / lane) — until
        PR 16 every route reused one "fp"-named mesh regardless of role."""
        mesh = self._meshes.get((size, axis))
        if mesh is None:
            from smartcal_tpu.parallel import make_mesh

            mesh = make_mesh((size,), (axis,),
                             devices=jax.devices()[:size])
            self._meshes[(size, axis)] = mesh
        return mesh

    def _mesh2(self, n_lane, n_baseline):
        """Cached composed lane x baseline mesh (parallel/mesh.compose_
        mesh): ONE topology the batched solve (P(lane) specs, baseline
        axis replicated) and the composed influence program share, so no
        resharding sits between them."""
        mesh = self._meshes.get((n_lane, n_baseline))
        if mesh is None:
            from smartcal_tpu.parallel import compose_mesh

            mesh = compose_mesh({AXIS_LANE: n_lane,
                                 AXIS_BASELINE: n_baseline})
            self._meshes[(n_lane, n_baseline)] = mesh
        return mesh

    def calibrate(self, ep: Episode, rho, mask=None, admm_iters=None):
        """Solve with per-direction rho; ``mask`` (K,) in {0,1} excludes
        directions by zeroing their model (static shapes, no recompile).
        Cold start: n_chunks (not J0) sets the solution intervals, so the
        solver's chi2-only init phase runs.

        Routing (untraced calls): with a usable mesh the solve runs
        frequency-sharded (parallel/sharded_cal.solve_admm_sharded — the
        consensus psum is the MPI allreduce as an ICI collective, and the
        per-shard program is 1/n-th the fused size, which also keeps it
        under the device watchdog).  Otherwise large problems route to
        the host-segmented driver (bounded device dispatches; a single
        fused XLA program running for minutes trips device/tunnel
        watchdogs — solver.solve_admm_host).  Under a jax trace (the
        vmapped hint sweep) the fused path is the only legal one and is
        kept.

        Precision: the solve runs f32 under EVERY backend ``precision``
        — the ``admm``/``hessian``/``solve_4n`` policy rows are pinned
        (cal/precision.py; measured — bf16 there fails the sigma_res
        parity band), so ``precision="bf16"`` affects only the
        influence/imaging chain.
        """
        C = ep.Ccal
        if mask is not None:
            C = C * jnp.asarray(mask)[None, :, None, None, None]
        traced = any(isinstance(x, jax.core.Tracer)
                     for x in (C, ep.V, rho, admm_iters))
        # solver telemetry rides along whenever a RunLog is recording
        # (untraced calls only: under a trace the output tree must stay
        # the callers' fused-solve shape).  With no RunLog active this is
        # collect_stats=False — the exact pre-observability programs.
        collect = (not traced) and obs.active() is not None
        if not traced:
            work = self._fused_work(admm_iters)
            # SMARTCAL_HOST_SOLVER=1 is the operational kill-switch for
            # everything but the bounded host-segmented driver (e.g. to
            # dodge a sharded/shard_map regression) — it must beat the
            # mesh route, not just the fused-vs-host heuristic
            forced_host = (os.environ.get("SMARTCAL_HOST_SOLVER", "")
                           .strip() == "1")
            nfp = 0 if forced_host else self._shard_size(self.n_freqs, work)

            def host_route(rho_arr):
                with obs.span("solve", route="host_segmented"):
                    return solver.solve_admm_host(
                        ep.V, C, ep.obs.freqs, ep.f0, jnp.asarray(rho_arr),
                        self._solver_cfg(ep.n_dirs), n_chunks=self.n_chunks,
                        admm_iters=None if admm_iters is None
                        else int(admm_iters), collect_stats=collect)

            if nfp and work / nfp <= _WATCHDOG_WORK:
                from smartcal_tpu.parallel import sharded_cal

                route = "sharded"

                def route_fn(rho_arr):
                    with obs.span("solve", route="sharded", shards=nfp):
                        return sharded_cal.solve_admm_sharded(
                            self._mesh(nfp, AXIS_FREQ), ep.V, C,
                            ep.obs.freqs, ep.f0,
                            jnp.asarray(rho_arr),
                            self._solver_cfg(ep.n_dirs),
                            axis=AXIS_FREQ, n_chunks=self.n_chunks,
                            admm_iters=None if admm_iters is None
                            else int(admm_iters), collect_stats=collect)
            elif self._use_host_solver(admm_iters):
                route, route_fn = "host_segmented", host_route
            else:
                route = "fused"

                def route_fn(rho_arr):
                    with obs.span("solve", route="fused"):
                        res = solver.solve_admm(
                            ep.V, C, ep.obs.freqs, ep.f0,
                            jnp.asarray(rho_arr),
                            self._solver_cfg(ep.n_dirs),
                            n_chunks=self.n_chunks,
                            admm_iters=None if admm_iters is None
                            else jnp.asarray(admm_iters),
                            collect_stats=collect)
                    # per-compile FLOPs/bytes accounting (no-op unless
                    # --diag armed it; cached per shape signature).  HLO
                    # counts the while_loop body once, so this is the
                    # roofline FLOOR — the per-iteration truth stays with
                    # solver.cost_eval_flops.
                    obs_costs.record_stage_cost(
                        "solve", solver.solve_admm, ep.V, C, ep.obs.freqs,
                        ep.f0, jnp.asarray(rho_arr),
                        self._solver_cfg(ep.n_dirs),
                        defer=True,      # still inside the env step span
                        n_chunks=self.n_chunks,
                        admm_iters=None if admm_iters is None
                        else jnp.asarray(admm_iters), collect_stats=collect)
                    return res

            res = route_fn(rho)
            res, route = self._robustify(
                res, route_fn, None if route == "host_segmented"
                else host_route, rho, route)
            return self._log_solve(res, route)
        return solver.solve_admm(
            ep.V, C, ep.obs.freqs, ep.f0, jnp.asarray(rho),
            self._solver_cfg(ep.n_dirs), n_chunks=self.n_chunks,
            admm_iters=None if admm_iters is None else jnp.asarray(admm_iters))

    def _robustify(self, res, route_fn, host_fn, rho, route):
        """Solver graceful degradation on the untraced routes: non-finite
        consensus iterates re-solve at boosted rho (bounded retries), then
        fall back to the host-segmented route, then surface
        SolverDegradedError — one bad episode degrades, never crashes, a
        batch.  Healthy solves pay one finiteness reduction.  Every
        degradation step emits a structured ``solver_degraded`` event."""
        override = os.environ.get("SMARTCAL_ROBUST_SOLVER", "").strip()
        enabled = (override == "1" if override in ("0", "1")
                   else self.robust_solver)
        if not enabled:
            return res, route
        final_route = [route]

        def on_event(**info):
            if info.get("route") == "host_segmented":
                final_route[0] = "host_segmented"
            rl = obs.active()
            if rl is not None:
                rl.log("solver_degraded", primary_route=route, **info)
            obs.echo(f"solver degraded ({route}): {info}", event=None)

        res, _ = solver.solve_admm_safe(
            route_fn, rho, initial_result=res, host_fallback=host_fn,
            max_retries=self.solver_max_retries,
            rho_boost=self.solver_rho_boost, on_event=on_event)
        return res, final_route[0]

    def _log_solve(self, res, route):
        """Record the solver telemetry event (no-op without a RunLog)."""
        if res.stats is not None and obs.active() is not None:
            obs.log_solver_stats(res.stats, route=route,
                                 n_freqs=self.n_freqs,
                                 n_stations=self.n_stations)
        return res

    def _use_host_solver(self, admm_iters=None) -> bool:
        """Proxy for 'one fused solve would run too long on a chip'
        (see _fused_work).  N=14/Nf=3 training configs stay fused (they
        live inside vmapped sweeps and finish in seconds); LOFAR-scale
        N=62/Nf=8 segments.  SMARTCAL_HOST_SOLVER=0/1 overrides."""
        override = os.environ.get("SMARTCAL_HOST_SOLVER", "").strip()
        if override in ("0", "1"):
            return override == "1"
        # calibration units: N=62/Nf=8 at few iterations (3.7e6) measured
        # ~10s steady on one v5e chip and runs fine; the watchdog bites
        # near ~60-90s (2-3e7).  1e7 =~ 35s leaves margin both ways.
        return self._fused_work(admm_iters) > _WATCHDOG_WORK

    def hint_sweep(self, ep: Episode, rho, masks, admm_iters=None,
                   batch=None):
        """Batched masked calibrations (the exhaustive AIC hint): the
        2^(K-1) configurations run as vmapped batches of ``batch`` masks
        (lax.map over batches bounds memory) instead of the reference's 32
        sequential MPI launches.

        Returns the STOKES-I residual statistic per mask — the same
        get_noise_-style quantity (demixingenv.py:233-252,322) the env
        reward and std_data use, so the hint's AIC residual term is on the
        same scale as the reward the agent is trained on (a full-pol RMS
        here would rescale it against the ksel*N complexity penalty)."""
        with obs.span("hint_sweep", n_masks=int(np.asarray(masks).shape[0])):
            return self._hint_sweep(ep, rho, masks, admm_iters, batch)

    def _hint_sweep(self, ep, rho, masks, admm_iters, batch):
        masks = jnp.asarray(masks, jnp.float32)
        n = int(masks.shape[0])
        batch = min(self.hint_batch if batch is None else batch, n)
        # One jitted program per (n_dirs, n, batch), with EVERY per-episode
        # value (V, C, freqs, f0, rho, masks, iteration count) as a traced
        # ARGUMENT.  The previous eager lax.map closed over the episode
        # arrays, embedding them as constants — a fresh trace + XLA compile
        # of the multi-minute solver program EVERY episode (and per maxiter
        # value), which dominated hint-arm wall-clock (~2-3 min/episode on
        # the CPU host, vs seconds of actual solve work).
        key = (ep.n_dirs, n, batch)
        fn = self._sweep_fns.get(key)
        if fn is None:
            cfg = self._solver_cfg(ep.n_dirs)
            n_chunks = self.n_chunks
            pad = (-n) % batch

            @jax.jit
            def fn(V, C, freqs, f0, rho_, masks_, iters):
                def one(mask):
                    Cm = C * mask[None, :, None, None, None]
                    res = solver.solve_admm(V, Cm, freqs, f0, rho_, cfg,
                                            n_chunks=n_chunks,
                                            admm_iters=iters)
                    stds = jax.vmap(solver.stokes_i_std)(res.residual)
                    return jnp.sqrt(jnp.mean(stds ** 2))

                if batch == 1:
                    # sequential lanes, no vmap: while_loops keep their
                    # per-lane early exits and cond stays a real branch
                    return jax.lax.map(one, masks_)
                padded = jnp.concatenate(
                    [masks_, jnp.zeros((pad,) + masks_.shape[1:],
                                       masks_.dtype)])
                chunks = padded.reshape(-1, batch, masks_.shape[1])
                return jax.lax.map(jax.vmap(one), chunks).reshape(-1)[:n]

            self._sweep_fns[key] = fn
        iters = self.admm_iters if admm_iters is None else admm_iters
        return fn(ep.V, ep.Ccal, ep.obs.freqs, jnp.asarray(ep.f0),
                  jnp.asarray(rho, jnp.float32), masks,
                  jnp.asarray(iters))

    @property
    def n_baselines(self):
        return self.n_stations * (self.n_stations - 1) // 2

    def _influence_statics(self, npix):
        """The SKA-tier static kwargs of the influence chain, decided on
        the HOST from the episode geometry (python-static by contract):
        blocked Hessian above the baseline threshold, blocked imager
        above the npix threshold, and the backend's precision policy."""
        bb = self.block_baselines
        if bb is None:
            bb = _BLOCK_BASELINES if self.n_baselines >= _BLOCK_MIN_B \
                else 0
        ibr = self.imager_block_r
        if ibr is None:
            ibr = _IMAGER_BLOCK_R if npix >= _IMAGER_BLOCK_MIN_NPIX else 0
        return {"block_baselines": bb, "imager_block_r": ibr,
                "precision": self.precision}

    def influence_image(self, ep: Episode, result: solver.SolveResult,
                        rho, rho_spatial, npix=None):
        """Mean influence dirty image over sub-bands (doinfluence.sh role).

        All production routes run the formulation-optimized chain
        (scatter-free Hessian, adjoint 4-RHS Dsolutions -> Dresiduals
        transpose solve, hoisted chunk/frequency invariants, rank-
        factored DFT imager — cal/influence, cal/kernels).  Routing:
        with a usable mesh the sub-bands fan out over devices
        (parallel/sharded_cal.influence_images_sharded); when the
        frequency axis doesn't divide but the chunk axis does, the
        per-band chunk-sharded kernel (influence_sharded — the
        reference's process pool as a mesh axis) is used instead; a
        single device above the watchdog work threshold segments per
        sub-band with double-buffered dispatches and a donated image
        carry; small problems run ONE fused dispatch for all sub-bands
        (cal/influence.influence_images_multi).  ``vectorized=False``
        keeps the original host loop on the ORACLE kernels (the parity
        oracle and the bench.py pre-optimization baseline).
        """
        with obs.span("influence") as sp:
            return self._influence_image(ep, result, rho, rho_spatial, npix,
                                         sp)

    def _influence_image(self, ep, result, rho, rho_spatial, npix, sp):
        npix = npix or self.npix
        freqs = np.asarray(ep.obs.freqs)
        if not self.vectorized:
            sp.tag(route="host_loop")
            return self._influence_image_loop(ep, result, rho, rho_spatial,
                                              npix)
        uvw = jnp.asarray(np.asarray(ep.obs.uvw).reshape(-1, 3))
        cell = imager.default_cell(ep.obs.uvw, float(freqs[-1]))
        # polytype matches the solve's consensus basis (the reference
        # hard-codes Bernstein here, analysis_torch.py:104 — a solver/
        # influence mismatch we do not reproduce)
        hadd_all = influence.consensus_hadd_all(
            rho, rho_spatial, freqs, ep.f0, n_poly=self.n_poly,
            polytype=self.polytype)                          # (Nf, K)
        # same size gate as the solve: influence cost tracks the episode
        # scale, and a backend big enough to shard the ADMM is big enough
        # to shard the influence fan-out
        work = self._fused_work()
        statics = self._influence_statics(npix)
        # baseline shard axis first at SKA scale: above the blocked
        # threshold the per-baseline tensors are the memory wall, and
        # partitioning B is what makes an N >= 256 episode FIT — the
        # frequency fan-out merely speeds it up
        if self.n_baselines >= _BLOCK_MIN_B:
            nbp = self._shard_size(self.n_baselines, work)
            if nbp:
                sp.tag(route="baseline_sharded", shards=nbp)
                out = self._influence_image_baseline_sharded(
                    ep, result, hadd_all, uvw, cell, npix, nbp, statics)
                self._record_influence_cost(result, ep, hadd_all, uvw,
                                            cell, npix, statics,
                                            shards={AXIS_BASELINE: nbp})
                return out
        nfp = self._shard_size(self.n_freqs, work)
        if nfp:
            from smartcal_tpu.parallel import sharded_cal

            sp.tag(route="freq_sharded", shards=nfp)
            out = sharded_cal.influence_images_sharded(
                self._mesh(nfp, AXIS_FREQ), result.residual, ep.Ccal,
                result.J, hadd_all, ep.obs.freqs, uvw, cell,
                self.n_stations, self.n_chunks, npix, axis=AXIS_FREQ,
                **statics)
            self._record_influence_cost(result, ep, hadd_all, uvw, cell,
                                        npix, statics,
                                        shards={AXIS_FREQ: nfp})
            return out
        nsp = self._shard_size(self.n_chunks, work)
        if nsp:
            sp.tag(route="chunk_sharded", shards=nsp)
            out = self._influence_image_chunk_sharded(
                ep, result, hadd_all, uvw, cell, npix, nsp, statics)
            self._record_influence_cost(result, ep, hadd_all, uvw, cell,
                                        npix, statics,
                                        shards={AXIS_CHUNK: nsp})
            return out
        if self._use_host_solver():
            # single device at watchdog scale: same proxy as the solve —
            # one fused all-band influence program runs minutes on a
            # chip, so segment per sub-band (bounded dispatches,
            # host-loop double-buffered)
            sp.tag(route="host_segmented", bands=self.n_freqs)
            return self._influence_image_host_segmented(
                ep, result, hadd_all, uvw, cell, npix, statics)
        sp.tag(route="vectorized")
        imgs = influence.influence_images_multi(
            result.residual, ep.Ccal, result.J, hadd_all, ep.obs.freqs,
            uvw, cell, self.n_stations, self.n_chunks, npix, **statics)
        self._record_influence_cost(result, ep, hadd_all, uvw, cell, npix,
                                    statics)
        return jnp.mean(imgs, axis=0)

    def _record_influence_cost(self, result, ep, hadd_all, uvw, cell, npix,
                               statics=None, shards=1):
        """Deferred cost-analysis event for the influence stage, shared by
        the vectorized and ALL sharded routes: shard_map programs don't
        AOT-lower through record_stage_cost's plain-args contract, so the
        sharded routes account the fused single-device equivalent — the
        same math (the shard only adds the reductions' psums), hence the
        right TOTAL stage flops for the roofline table.  ``shards``
        divides the footprint fields (obs/costs.py): per-device peak
        live bytes under the sharded routes."""
        statics = statics or {}
        from smartcal_tpu.cal import precision as _prec

        obs_costs.record_stage_cost(
            "influence", influence.influence_images_multi,
            result.residual, ep.Ccal, result.J, hadd_all, ep.obs.freqs,
            uvw, static_argnames=("cell", "n_stations", "n_chunks", "npix",
                                  "block_baselines", "imager_block_r",
                                  "precision"),
            defer=True,              # inside the influence span
            shards=shards,
            compute_dtype=_prec.dtype_name(_prec.contraction_dtype(
                "imager_matmul", statics.get("precision", "f32"))),
            cell=cell, n_stations=self.n_stations, n_chunks=self.n_chunks,
            npix=npix, **statics)
        self._record_kernel_costs(ep.n_dirs, npix, cell, statics)

    def _record_kernel_costs(self, n_dirs, npix, cell, statics=None):
        """Kernel-family roofline rows (ISSUE 17): when a blocked tier
        is engaged, record BOTH implementations of the kernel — the
        blocked XLA path and its tiled pallas twin — as
        ``kernel:<name>`` cost events lowered from shape-only operands,
        so tools/obs_report.py can print the pallas-vs-XLA comparison
        that gates kernel promotion.  The pallas rows lower the real
        Mosaic kernel on TPU and the interpreter form elsewhere —
        interpreter numbers certify parity and plumbing, only the TPU
        rows are rooflines.  Deferred and deduped by abstract signature
        like every cost event."""
        from smartcal_tpu.cal import kernels as _kernels
        from smartcal_tpu.ops import pallas_hessian, pallas_imager

        statics = statics or self._influence_statics(npix)
        sds = jax.ShapeDtypeStruct
        f32 = jnp.float32
        K, B = n_dirs, self.n_baselines
        Td = max(self.n_times // self.n_chunks, 1)
        R = self.n_times * B
        on_tpu = pallas_imager.pallas_available()
        bb = statics.get("block_baselines", 0)
        if bb:
            r3 = sds((Td, B, 2, 2, 2), f32)
            c5 = sds((K, Td, B, 2, 2, 2), f32)
            jb = sds((K, B, 2, 2, 2), f32)
            obs_costs.record_stage_cost(
                "kernel:hessian_blocked_xla",
                _kernels._hessian_res_core_blocked_sr, r3, c5, jb, jb,
                static_argnames=("n_stations", "block_baselines"),
                defer=True, n_stations=self.n_stations,
                block_baselines=bb)
            obs_costs.record_stage_cost(
                "kernel:hessian_pallas",
                pallas_hessian.hessian_res_core_pallas_sr, r3, c5, jb,
                jb, static_argnames=("n_stations", "interpret"),
                defer=True, n_stations=self.n_stations,
                interpret=not on_tpu)
        ibr = statics.get("imager_block_r", 0)
        if ibr:
            uvw_s = sds((R, 3), f32)
            vis_s = sds((R, 2), f32)
            freq_s = sds((), f32)
            prec_s = statics.get("precision", "f32")
            obs_costs.record_stage_cost(
                "kernel:imager_blocked_xla",
                imager.dirty_image_factored_blocked_sr, uvw_s, vis_s,
                freq_s, float(cell),
                static_argnames=("npix", "block_r", "precision"),
                defer=True, npix=npix, block_r=ibr, precision=prec_s)
            if npix % pallas_imager.TILE_L == 0:
                obs_costs.record_stage_cost(
                    "kernel:imager_pallas",
                    pallas_imager.dirty_image_factored_pallas, uvw_s,
                    vis_s, freq_s, float(cell),
                    static_argnames=("npix", "precision", "interpret"),
                    defer=True, npix=npix, precision=prec_s,
                    interpret=not on_tpu)

    def _influence_image_host_segmented(self, ep, result, hadd_all, uvw,
                                        cell, npix, statics=None):
        """Per-sub-band influence images as bounded device dispatches
        (cal/influence.influence_image_single_sr), double-buffered by
        JAX's async dispatch: band f+1's program is enqueued while band
        f executes, with no host sync until the final mean.  The running
        image sum is a DONATED carry (``_img_acc``), so on accelerators
        each band's accumulation reuses the previous buffer instead of
        allocating Nf images."""
        from smartcal_tpu.cal import precision as _prec

        statics = statics if statics is not None \
            else self._influence_statics(npix)
        freqs_arr = jnp.asarray(np.asarray(ep.obs.freqs), _prec.F32)
        acc = None
        for fi in range(self.n_freqs):
            img = influence.influence_image_single_sr(
                result.residual[fi], ep.Ccal[fi], result.J[fi],
                hadd_all[fi], freqs_arr[fi], uvw, cell,
                n_stations=self.n_stations, n_chunks=self.n_chunks,
                npix=npix, **statics)
            acc = img if acc is None else _img_acc(acc, img)
        obs_costs.record_stage_cost(
            "influence", influence.influence_image_single_sr,
            result.residual[0], ep.Ccal[0], result.J[0], hadd_all[0],
            freqs_arr[0], uvw, cell, defer=True,  # inside the span
            compute_dtype=_prec.dtype_name(_prec.contraction_dtype(
                "imager_matmul", statics.get("precision", "f32"))),
            n_stations=self.n_stations, n_chunks=self.n_chunks, npix=npix,
            **statics)
        return acc / self.n_freqs

    def _influence_image_chunk_sharded(self, ep, result, hadd_all, uvw,
                                       cell, npix, nsp, statics=None):
        """Per-band influence with the calibration-interval axis sharded
        (sharded_cal.influence_sharded); used when Nf has no usable
        divisor but n_chunks does."""
        from smartcal_tpu.parallel import sharded_cal

        statics = statics if statics is not None \
            else self._influence_statics(npix)
        mesh = self._mesh(nsp, AXIS_CHUNK)
        freqs = np.asarray(ep.obs.freqs)
        imgs = []
        for fi in range(self.n_freqs):
            Rk = solver.residual_to_kernel(result.residual[fi])
            inf = sharded_cal.influence_sharded(
                mesh, Rk, ep.Ccal[fi], result.J[fi], hadd_all[fi],
                self.n_stations, self.n_chunks, axis=AXIS_CHUNK,
                block_baselines=statics["block_baselines"],
                precision=statics.get("precision", "f32"))
            ivis = influence.stokes_i_influence(inf.vis)
            imgs.append(self._image_ivis(uvw, ivis, float(freqs[fi]),
                                         cell, npix, statics))
        return jnp.mean(jnp.stack(imgs), axis=0)

    def _image_ivis(self, uvw, ivis, freq, cell, npix, statics):
        """Factored DFT image of one band's influence visibilities with
        the SKA-tier statics applied (blocked imager above the npix
        threshold, precision policy).  Runs OUTSIDE the shard_map (the
        vis are already gathered), so the large-tier dispatch may pick
        the Pallas tile kernel on TPU."""
        if statics.get("imager_block_r"):
            return imager.dirty_image_factored_large_sr(
                uvw, ivis, freq, cell, npix=npix,
                block_r=statics["imager_block_r"],
                precision=statics.get("precision", "f32"))
        return imager.dirty_image_factored_sr(
            uvw, ivis, freq, cell, npix=npix,
            precision=statics.get("precision", "f32"))

    def _influence_image_baseline_sharded(self, ep, result, hadd_all, uvw,
                                          cell, npix, nbp, statics):
        """Per-band influence with the BASELINE axis sharded
        (sharded_cal.influence_baseline_sharded) — the SKA-scale route:
        the (B, ...) residual/coherency/lhs tensors and every
        per-baseline einsum temporary partition across the mesh, so an
        N >= 256 episode's influence chain fits where the unsharded
        chain is footprint-bounded.  The mesh axis carries the registry
        baseline name (AXIS_BASELINE) — the pre-registry kludge of
        reusing the "fp"-named generic mesh for the baseline ROLE is
        gone (ISSUE 17 satellite 2)."""
        from smartcal_tpu.parallel import sharded_cal

        mesh = self._mesh(nbp, AXIS_BASELINE)
        freqs = np.asarray(ep.obs.freqs)
        imgs = []
        for fi in range(self.n_freqs):
            Rk = solver.residual_to_kernel(result.residual[fi])
            inf = sharded_cal.influence_baseline_sharded(
                mesh, Rk, ep.Ccal[fi], result.J[fi], hadd_all[fi],
                self.n_stations, self.n_chunks, axis=AXIS_BASELINE,
                precision=statics.get("precision", "f32"))
            ivis = influence.stokes_i_influence(inf.vis)
            imgs.append(self._image_ivis(uvw, ivis, float(freqs[fi]),
                                         cell, npix, statics))
        return jnp.mean(jnp.stack(imgs), axis=0)

    def _influence_image_loop(self, ep, result, rho, rho_spatial, npix):
        """The original per-frequency host loop (pre-pipeline path): kept
        as the parity oracle for the vectorized/sharded kernels and the
        bench.py host-loop baseline — ``optimized=False`` pins it to the
        oracle influence kernels and the direct-DFT imager, so the
        host-loop arm keeps measuring the PRE-optimization formulation."""
        freqs = np.asarray(ep.obs.freqs)
        hadd_all = [influence.consensus_hadd_scalars(
            rho, rho_spatial, freqs, ep.f0, fi, n_poly=self.n_poly,
            polytype=self.polytype) for fi in range(self.n_freqs)]
        uvw = jnp.asarray(np.asarray(ep.obs.uvw).reshape(-1, 3))
        cell = imager.default_cell(ep.obs.uvw, float(freqs[-1]))
        imgs = []
        for fi in range(self.n_freqs):
            Rk = solver.residual_to_kernel(result.residual[fi])
            inf = influence.influence_visibilities(
                Rk, ep.Ccal[fi], result.J[fi], hadd_all[fi],
                self.n_stations, self.n_chunks, optimized=False)
            ivis = influence.stokes_i_influence(inf.vis)
            imgs.append(imager.dirty_image_sr(uvw, ivis, float(freqs[fi]),
                                              cell, npix=npix))
        return jnp.mean(jnp.stack(imgs), axis=0)

    def data_image(self, ep: Episode, npix=None):
        cell = imager.default_cell(ep.obs.uvw,
                                   float(np.asarray(ep.obs.freqs)[-1]))
        return imager.multifreq_image_sr(ep.obs.uvw, ep.V, ep.obs.freqs,
                                         cell, npix=npix or self.npix)

    def residual_image(self, ep: Episode, result: solver.SolveResult,
                       npix=None):
        cell = imager.default_cell(ep.obs.uvw,
                                   float(np.asarray(ep.obs.freqs)[-1]))
        return imager.multifreq_image_sr(ep.obs.uvw, result.residual,
                                         ep.obs.freqs, cell,
                                         npix=npix or self.npix)

    def noise_std(self, V):
        """sqrt(mean_f std(Stokes I)^2) — the reference's get_noise_
        (demixingenv.py:233-252) over MS columns."""
        stds = jax.vmap(solver.stokes_i_std)(V)
        return jnp.sqrt(jnp.mean(stds ** 2))

    # -- batched-episode mode ------------------------------------------------
    #
    # PR 1/5 made the whole simulate -> ADMM -> influence chain a pure,
    # keyed, static-shape, matmul-only function — exactly the shape vmap
    # wants.  The methods below run B independent episodes as ONE batched
    # program over a leading lane axis: a vmapped fused solve on a single
    # device, or a shard_map over the lane axis when a mesh divides the
    # batch (each lane keeps its full frequency axis locally, so no
    # collective crosses an episode boundary; the 2D batch x frequency
    # mesh form lives in parallel/sharded_cal.solve_admm_sharded2d).
    # The per-lane sequential methods above REMAIN the parity oracle —
    # the batched envs route through them under ``fused=False``.

    def stack_episodes(self, eps) -> BatchedEpisode:
        """Stack per-lane :class:`Episode`s into one :class:`BatchedEpisode`
        (the batching boundary — see BatchedEpisode docstring)."""
        from smartcal_tpu.cal import imager

        n_dirs = eps[0].n_dirs
        assert all(e.n_dirs == n_dirs for e in eps), \
            "batched lanes must share a (padded) direction count"
        freqs = np.stack([np.asarray(e.obs.freqs) for e in eps])
        return BatchedEpisode(
            V=jnp.stack([e.V for e in eps]),
            Ccal=jnp.stack([e.Ccal for e in eps]),
            freqs=freqs,
            f0=np.asarray([e.f0 for e in eps], np.float32),
            uvw=np.stack([np.asarray(e.obs.uvw).reshape(-1, 3)
                          for e in eps]),
            cell=np.asarray([imager.default_cell(e.obs.uvw,
                                                 float(freqs[i][-1]))
                             for i, e in enumerate(eps)], np.float32),
            n_dirs=n_dirs)

    def splice_episode(self, bep: BatchedEpisode, lane: int,
                       ep: Episode) -> BatchedEpisode:
        """Replace lane ``lane`` of ``bep`` with a fresh episode (masked
        reset): the V/Ccal batch buffers update through the DONATED
        ``_lane_splice`` (in-place on accelerators, no recompile — the
        lane index is traced), the small host fields through numpy."""
        from smartcal_tpu.cal import imager

        assert ep.n_dirs == bep.n_dirs
        freqs = np.asarray(ep.obs.freqs)
        f0 = bep.f0.copy()
        f0[lane] = ep.f0
        freqs_b = bep.freqs.copy()
        freqs_b[lane] = freqs
        uvw = bep.uvw.copy()
        uvw[lane] = np.asarray(ep.obs.uvw).reshape(-1, 3)
        cell = bep.cell.copy()
        cell[lane] = imager.default_cell(ep.obs.uvw, float(freqs[-1]))
        lane_ = jnp.asarray(lane, jnp.int32)
        return bep._replace(
            V=_lane_splice(bep.V, ep.V, lane_),
            Ccal=_lane_splice(bep.Ccal, ep.Ccal, lane_),
            freqs=freqs_b, f0=f0, uvw=uvw, cell=cell)

    def _batch_shard_size(self, n_lanes):
        """Lane-axis mesh size for the batched routes (0 = run the plain
        vmap): same policy as the per-episode ``_shard_size`` — the work
        gate uses the whole BATCH's calibration units, since that is the
        one fused program's size."""
        return self._shard_size(n_lanes, self._fused_work() * n_lanes)

    def _compose_sizes(self, n_lanes):
        """(n_lane, n_baseline) shape of the composed batched mesh
        (ISSUE 17): lanes fill the mesh first (independent episodes are
        the cheapest parallelism — no collectives), and leftover devices
        go to the baseline axis only in the blocked-B tier
        (``n_baselines >= _BLOCK_MIN_B``), where partitioning B is what
        makes the program FIT rather than merely faster.
        ``SMARTCAL_COMPOSE=1`` forces the baseline axis on below the
        tier (tests/bench arms); ``=0`` disables it.  ``n_baseline`` is
        0 when the composed program would degenerate to lane-only."""
        nl = self._batch_shard_size(n_lanes)
        env = os.environ.get("SMARTCAL_COMPOSE", "").strip().lower()
        if env in ("0", "false", "no", "off"):
            return nl, 0
        spare = jax.device_count() // max(nl, 1)
        want_b = env in ("1", "true", "yes", "on") or \
            self.n_baselines >= _BLOCK_MIN_B
        if not want_b or spare < 2:
            return nl, 0
        nb = largest_divisor(self.n_baselines, spare)
        return nl, (nb if nb >= 2 else 0)

    def batched_solve_callable(self, n_dirs):
        """The UNJITTED vmapped masked-ADMM solve over a leading lane
        axis — positional operands as built by
        :meth:`batched_solve_operands`.  Public so the serving layer
        (serve/export.py) can AOT-export EXACTLY the program
        :meth:`calibrate_batched` jits: one definition, two compilation
        paths, no parity gap."""
        cfg = self._solver_cfg(n_dirs)
        n_chunks = self.n_chunks

        def one(v, c, f, f0_, r, m, it):
            cm = c * m[None, :, None, None, None]
            return solver.solve_admm(v, cm, f, f0_, r, cfg,
                                     n_chunks=n_chunks, admm_iters=it)

        return jax.vmap(one)

    def _batched_solve_fn(self, n_dirs, n_lanes, nbp, nb=0):
        key = ("solve", n_dirs, n_lanes, nbp, nb)
        fn = self._batched_fns.get(key)
        if fn is not None:
            return fn
        inner = self.batched_solve_callable(n_dirs)
        if nbp:
            from jax.sharding import PartitionSpec as P

            from smartcal_tpu.parallel import sharded_cal

            # composed topology (ISSUE 17): when the influence chain
            # shards lanes x baselines, the solve runs on the SAME mesh
            # with the baseline axis replicated — learner, solve and
            # influence share one topology, so the solve -> influence
            # hand-off never reshards
            mesh = self._mesh2(nbp, nb) if nb else \
                self._mesh(nbp, AXIS_LANE)
            ax = AXIS_LANE
            out_specs = solver.SolveResult(
                J=P(ax), Z=P(ax), residual=P(ax), sigma_res=P(ax),
                sigma_data=P(ax), final_cost=P(ax), stats=None)
            inner = sharded_cal.shard_map(
                inner, mesh=mesh, in_specs=(P(ax),) * 7,
                out_specs=out_specs)
        fn = jax.jit(inner)
        self._batched_fns[key] = fn
        return fn

    def batched_solve_operands(self, bep: BatchedEpisode, rho, mask=None,
                               admm_iters=None) -> tuple:
        """The positional operand tuple of the batched solve program
        (shared by :meth:`calibrate_batched` and the serving layer's
        exported call — the operand layout IS the export ABI)."""
        E = int(bep.V.shape[0])
        rho = jnp.asarray(rho, jnp.float32).reshape(E, bep.n_dirs)
        masks = (jnp.ones((E, bep.n_dirs), jnp.float32) if mask is None
                 else jnp.asarray(mask, jnp.float32).reshape(E, bep.n_dirs))
        if admm_iters is None:
            iters = jnp.full((E,), self.admm_iters, jnp.int32)
        else:
            iters = jnp.broadcast_to(
                jnp.asarray(admm_iters, jnp.int32).reshape(-1), (E,))
        return (bep.V, bep.Ccal, jnp.asarray(bep.freqs),
                jnp.asarray(bep.f0, jnp.float32), rho, masks, iters)

    def calibrate_batched(self, bep: BatchedEpisode, rho, mask=None,
                          admm_iters=None,
                          compose=None) -> solver.SolveResult:
        """Batched :meth:`calibrate`: B lanes' masked ADMM solves as ONE
        program.  ``rho`` (E, K) per-lane regularization; ``mask``
        (E, K) in {0, 1} (None = all directions); ``admm_iters`` a
        scalar, an (E,) per-lane iteration count (the demixing action's
        maxiter), or None for the constructor default.  Every per-lane
        value is a traced argument, so one compile serves every episode
        batch of this shape.  Solver stats are not collected on this
        route (the batched program's output tree stays the fused-solve
        shape, same rule as the traced hint sweep).

        ``compose`` forces the ``(n_lane, n_baseline)`` mesh shape
        (None = the :meth:`_compose_sizes` policy); a baseline size
        >= 2 places the solve on the composed lane x baseline mesh with
        the baseline axis replicated, so it shares the influence
        chain's topology."""
        E = int(bep.V.shape[0])
        nl, nb = self._compose_sizes(E) if compose is None \
            else (int(compose[0]), int(compose[1]))
        route = "batched_sharded" if nl else "batched_vmap"
        fn = self._batched_solve_fn(bep.n_dirs, E, nl, nb if nl else 0)
        ops = self.batched_solve_operands(bep, rho, mask, admm_iters)
        with obs.span("solve", route=route, lanes=E,
                      **({"shards": nl} if nl else {}),
                      **({"baseline_shards": nb} if nl and nb else {})):
            obs.gauge_set("batched_lanes", E)
            return fn(*ops)

    def batched_influence_callable(self, n_dirs, npix):
        """The UNJITTED vmapped influence chain (consensus Hessian-add ->
        multi-frequency influence images -> frequency mean) — positional
        operands as built by :meth:`batched_influence_operands`.  Public
        for the same reason as :meth:`batched_solve_callable`."""
        statics = self._influence_statics(npix)
        n_stations, n_chunks = self.n_stations, self.n_chunks
        n_poly, polytype = self.n_poly, self.polytype

        def one(res, c, j, r, a, f, f0_, u, cl):
            hadd = influence.consensus_hadd_all(
                r, a, f, f0_, n_poly=n_poly, polytype=polytype)
            imgs = influence.influence_images_multi(
                res, c, j, hadd, f, u, cl, n_stations, n_chunks, npix,
                **statics)
            return jnp.mean(imgs, axis=0)

        return jax.vmap(one)

    def _batched_influence_fn(self, n_dirs, n_lanes, npix):
        statics = self._influence_statics(npix)
        key = ("influence", n_dirs, n_lanes, npix,
               tuple(sorted(statics.items())))
        fn = self._batched_fns.get(key)
        if fn is not None:
            return fn
        fn = jax.jit(self.batched_influence_callable(n_dirs, npix))
        self._batched_fns[key] = fn
        return fn

    def batched_influence_operands(self, bep: BatchedEpisode,
                                   result: solver.SolveResult, rho,
                                   rho_spatial) -> tuple:
        """Positional operand tuple of the batched influence program
        (the serving export ABI, mirrored by
        :meth:`influence_images_batched`)."""
        E = int(bep.V.shape[0])
        rho = jnp.asarray(rho, jnp.float32).reshape(E, bep.n_dirs)
        alpha = jnp.asarray(rho_spatial, jnp.float32).reshape(E, bep.n_dirs)
        return (result.residual, bep.Ccal, result.J, rho, alpha,
                jnp.asarray(bep.freqs), jnp.asarray(bep.f0, jnp.float32),
                jnp.asarray(bep.uvw), jnp.asarray(bep.cell))

    def influence_images_batched(self, bep: BatchedEpisode,
                                 result: solver.SolveResult, rho,
                                 rho_spatial, npix=None, compose=None):
        """Batched :meth:`influence_image`: (E, npix, npix) mean influence
        dirty images, the whole formulation-optimized chain (scatter-free
        Hessian, adjoint 4-RHS transpose solve, rank-factored DFT imager
        — matmul-only, so it vmaps/shards cleanly) over the lane axis in
        one dispatch.  ``rho``/``rho_spatial`` are (E, K) per lane.

        ``compose`` forces the ``(n_lane, n_baseline)`` mesh shape
        (None = the :meth:`_compose_sizes` policy).  A baseline size
        >= 2 routes through the composed lane x baseline ``shard_map``
        program (parallel/sharded_cal.influence_images_batched_sharded)
        — the ISSUE 17 tentpole route: one program shards BOTH axes,
        with the Hessian/adjoint/imager collectives confined to the
        baseline axis."""
        E = int(bep.V.shape[0])
        npix = npix or self.npix
        nl, nb = self._compose_sizes(E) if compose is None \
            else (int(compose[0]), int(compose[1]))
        ops = self.batched_influence_operands(bep, result, rho, rho_spatial)
        statics = self._influence_statics(npix)
        if nb >= 2:
            from smartcal_tpu.parallel import sharded_cal

            nl = max(int(nl), 1)
            with obs.span("influence") as sp:
                sp.tag(route="batched_lane_bshard", lanes=E,
                       lane_shards=nl, baseline_shards=nb)
                out = sharded_cal.influence_images_batched_sharded(
                    self._mesh2(nl, nb), *ops, self.n_stations,
                    self.n_chunks, npix, n_poly=self.n_poly,
                    polytype=self.polytype,
                    imager_block_r=statics["imager_block_r"],
                    precision=statics["precision"])
                self._record_batched_influence_cost(
                    bep, ops, npix, statics,
                    shards={AXIS_LANE: nl, AXIS_BASELINE: nb})
            return out
        fn = self._batched_influence_fn(bep.n_dirs, E, npix)
        with obs.span("influence") as sp:
            sp.tag(route="batched_vmap", lanes=E)
            return fn(*ops)

    def _record_batched_influence_cost(self, bep, ops, npix, statics,
                                       shards):
        """Deferred cost event for the batched influence routes: like
        :meth:`_record_influence_cost`, the sharded route accounts the
        fused (vmapped) single-device equivalent and divides the
        footprint by the per-axis ``shards`` mapping — the composed
        mesh's per-device peak, broken out per axis in obs_report."""
        from smartcal_tpu.cal import precision as _prec

        obs_costs.record_stage_cost(
            "influence", self.batched_influence_callable(bep.n_dirs,
                                                         npix),
            *ops, defer=True, shards=shards,
            compute_dtype=_prec.dtype_name(_prec.contraction_dtype(
                "imager_matmul", statics.get("precision", "f32"))))
        self._record_kernel_costs(bep.n_dirs, npix,
                                  float(np.asarray(bep.cell)[0]), statics)

    def _batched_sigma_fn(self, n_lanes, npix):
        key = ("sigmas", n_lanes, npix)
        fn = self._batched_fns.get(key)
        if fn is not None:
            return fn
        from smartcal_tpu.cal import imager

        def one(v, res, f, u, cl):
            def img_std(x):
                imgs = jax.vmap(lambda vv, ff: imager.dirty_image_factored_sr(
                    u, imager.stokes_i_vis(vv), ff, cl, npix=npix))(x, f)
                return jnp.std(jnp.mean(imgs, axis=0))

            return img_std(v), img_std(res)

        fn = jax.jit(jax.vmap(one))
        self._batched_fns[key] = fn
        return fn

    def image_sigmas_batched(self, bep: BatchedEpisode,
                             result: solver.SolveResult, npix=None):
        """Per-lane (sigma_data_img, sigma_res_img) — the std of the
        multi-frequency data and residual dirty images (the CalibEnv
        reward inputs) for all lanes in one dispatch.  Uses the
        rank-factored DFT imager (same math as the oracle's XLA imager
        to float round-off; matmul-only, so it batches)."""
        npix = npix or self.npix
        fn = self._batched_sigma_fn(int(bep.V.shape[0]), npix)
        with obs.span("reward", route="batched_vmap"):
            return fn(bep.V, result.residual, jnp.asarray(bep.freqs),
                      jnp.asarray(bep.uvw), jnp.asarray(bep.cell))

    def noise_std_batched(self, V):
        """Per-lane :meth:`noise_std` over a (E, Nf, ...) batch in one
        dispatch."""
        key = ("noise_std",)
        fn = self._batched_fns.get(key)
        if fn is None:
            def one(v):
                stds = jax.vmap(solver.stokes_i_std)(v)
                return jnp.sqrt(jnp.mean(stds ** 2))

            fn = jax.jit(jax.vmap(one))
            self._batched_fns[key] = fn
        return fn(V)

    def serve_signature(self, n_dirs, n_lanes, npix=None) -> dict:
        """The STATIC trace signature of the batched solve/influence
        programs: every constructor knob that selects a different trace
        (and therefore a different executable), plus the lane/direction/
        image geometry.  The serving layer keys its AOT-export cache on
        this dict — two backends with equal signatures compile (and can
        reuse) the identical program."""
        return {
            "n_stations": self.n_stations, "n_freqs": self.n_freqs,
            "n_times": self.n_times, "tdelta": self.tdelta,
            "n_poly": self.n_poly, "polytype": self.polytype,
            "lbfgs_iters": self.lbfgs_iters, "init_iters": self.init_iters,
            "K": int(n_dirs), "lanes": int(n_lanes),
            "npix": int(npix or self.npix), "precision": self.precision,
            "block_baselines": self.block_baselines,
            "imager_block_r": self.imager_block_r,
        }
