"""Shared episode backend for the radio RL environments.

The reference envs drive an external pipeline per episode/step
(``calibenv.py`` shells dosimul.sh/docal.sh/doinfluence.sh,
``demixingenv.py`` shells mpirun sagecal-mpi + excon): simulate an
observation, calibrate it, compute influence maps, and read noise
statistics back from files.  Here the same contract is served by the
in-framework backend (cal/*): everything below the env API is jit-compiled
JAX on device, and one episode's data lives in device arrays, not an MS on
disk.

Static-shape design (the TPU-first move): instead of rewriting cluster
files per action like the reference, direction selection is a MASK over a
fixed K-direction coherency tensor — unselected directions have their
coherencies zeroed, so one compiled solver serves every subset, and the
2^(K-1) exhaustive hint sweep becomes a single vmap over masks rather than
the reference's 32 sequential MPI launches (demixingenv.py:301-336).
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from smartcal_tpu.cal import (coherency, imager, influence, observation,
                              simulate, solver)


class Episode(NamedTuple):
    """Device-resident state of one simulated observation."""

    obs: observation.Observation
    V: jnp.ndarray          # (Nf, T, B, 2, 2, 2) observed (corrupted+noise)
    Ccal: jnp.ndarray       # (Nf, K, T*B, 4, 2) calibration-model coherencies
    f0: float
    n_dirs: int
    snr: float


class RadioBackend:
    """Hermetic observation + calibration service for the envs.

    n_times = Ts * tdelta total integration slots; every ``tdelta`` slots
    share one solution interval (sagecal -t).
    """

    def __init__(self, n_stations=14, n_freqs=3, n_times=20, tdelta=10,
                 n_poly=2, admm_iters=10, lbfgs_iters=8, init_iters=30,
                 polytype=0, npix=128, hint_batch=8):
        if n_times <= 0 or n_times % tdelta != 0:
            raise ValueError(
                f"n_times={n_times} must be a positive multiple of "
                f"tdelta={tdelta}: every solution interval needs the same "
                "number of slots (vis_to_chunks/coherency_to_chunks reshape "
                "by Ts)")
        self.n_stations = n_stations
        self.n_freqs = n_freqs
        self.n_times = n_times
        self.tdelta = tdelta
        self.n_chunks = n_times // tdelta
        self.n_poly = n_poly
        self.admm_iters = admm_iters
        self.lbfgs_iters = lbfgs_iters
        self.init_iters = init_iters
        self.polytype = polytype
        self.npix = npix
        # hint-sweep vmap width: on accelerators wide lanes win; on CPU
        # vmapped while_loops cost every lane the worst lane's iteration
        # count (and cond becomes select), so hint_batch=1 (sequential
        # lax.map, per-lane early exit) is faster on one core
        self.hint_batch = hint_batch
        self._sweep_fns = {}     # (n_dirs, n_masks, batch) -> jitted sweep

    # -- episode construction ------------------------------------------------

    def _coherencies(self, obs, sky):
        uvw = np.asarray(obs.uvw).reshape(-1, 3)
        return jnp.stack([
            coherency.predict_coherencies_sr(uvw[:, 0], uvw[:, 1], uvw[:, 2],
                                             sky, f)
            for f in np.asarray(obs.freqs)])

    def _corrupt_and_noise(self, key, obs, Csim, J_extra_dirs, snr,
                           amp, spatial_term, lm_dirs):
        """Predict DATA: corrupt the sim sky with synthetic systematics and
        add noise (roles of sagecal -p sim + addnoise.py)."""
        K_sim = Csim.shape[1]
        n_err = K_sim - J_extra_dirs
        Jerr = simulate.synth_solutions(
            key, n_err, self.n_stations, self.n_chunks, np.asarray(obs.freqs),
            float(np.asarray(obs.freqs).mean()), amp=amp,
            spatial_term=spatial_term, lm_dirs=lm_dirs)
        Jid = simulate.identity_solutions(J_extra_dirs, self.n_stations,
                                          self.n_chunks, self.n_freqs)
        Jsim = np.concatenate([Jerr, Jid], axis=2)
        V = jnp.stack([
            solver.simulate_vis_sr(jnp.asarray(Jsim[f]), Csim[f],
                                   self.n_stations, self.n_chunks)
            for f in range(self.n_freqs)])
        Vn, _ = simulate.add_noise(key, np.asarray(V), snr=snr)
        return jnp.asarray(Vn)

    def _add_shapelet(self, obs, C, coeff, beta, flux):
        """Add a diffuse shapelet component to cluster 0 of a coherency
        tensor (cal/shapelets.py; the role of SAGECal's in-solver shapelet
        prediction for the reference's random diffuse sky)."""
        from smartcal_tpu.cal import shapelets

        uvw = np.asarray(obs.uvw).reshape(-1, 3)
        add = jnp.stack([
            shapelets.shapelet_coherency_sr(coeff, uvw[:, 0], uvw[:, 1],
                                            float(f), beta, flux=flux)
            for f in np.asarray(obs.freqs)])
        return C.at[:, 0].add(add)

    def new_calib_episode(self, key, K, M, diffuse=False):
        """CalibEnv episode: K drawn clusters padded to M directions.
        Returns (episode, models) with Ccal zero-padded to M directions."""
        obs = observation.make_observation(
            key, n_stations=self.n_stations, n_freqs=self.n_freqs,
            n_times=self.n_times)
        mdl = simulate.simulate_models(key, K=K, f0=float(
            np.asarray(obs.freqs).mean()), diffuse=diffuse)
        Csim = self._coherencies(obs, mdl.sky_sim)
        if mdl.shapelet is not None:
            Csim = self._add_shapelet(obs, Csim, mdl.shapelet.coeff,
                                      mdl.shapelet.beta, mdl.shapelet.flux)
        V = self._corrupt_and_noise(key, obs, Csim, J_extra_dirs=1, snr=0.05,
                                    amp=1.0, spatial_term=True,
                                    lm_dirs=mdl.lm_dirs)
        Ck = self._coherencies(obs, mdl.sky_cal)
        if mdl.shapelet is not None:
            Ck = self._add_shapelet(obs, Ck, mdl.shapelet.coeff_cal,
                                    mdl.shapelet.beta_cal,
                                    mdl.shapelet.flux)
        pad = M - K
        Ccal = jnp.pad(Ck, ((0, 0), (0, pad), (0, 0), (0, 0), (0, 0)))
        ep = Episode(obs=obs, V=V, Ccal=Ccal, f0=mdl.f0, n_dirs=M, snr=0.05)
        return ep, mdl

    def new_demixing_episode(self, key, K):
        """DemixingEnv episode: K-1 A-team outliers + target."""
        rng = observation.host_rng(key, salt=20)
        strategy = int(rng.integers(0, 3))
        ra0, dec0, t0 = observation.find_valid_target(
            key, strategy=1 if strategy == 1 else 0)
        hba = bool(rng.integers(0, 2))
        obs = observation.make_observation(
            key, n_stations=self.n_stations, n_freqs=self.n_freqs,
            n_times=self.n_times, hba=hba, ra0=ra0, dec0=dec0, t0=t0)
        f0 = float(np.asarray(obs.freqs).mean())
        mdl = simulate.simulate_demixing_sky(key, ra0, dec0, t0, f0, K=K)
        Csim = self._coherencies(obs, mdl.sky_sim)
        snr = float(0.05 + rng.random() * 0.45)
        V = self._corrupt_and_noise(key, obs, Csim, J_extra_dirs=1, snr=snr,
                                    amp=0.01, spatial_term=False,
                                    lm_dirs=mdl.lm_dirs)
        Ccal = self._coherencies(obs, mdl.sky_cal)
        ep = Episode(obs=obs, V=V, Ccal=Ccal, f0=f0, n_dirs=K, snr=snr)
        return ep, mdl

    # -- calibration + influence --------------------------------------------

    def _solver_cfg(self, K):
        return solver.SolverConfig(
            n_stations=self.n_stations, n_dirs=K, n_poly=self.n_poly,
            admm_iters=self.admm_iters, lbfgs_iters=self.lbfgs_iters,
            init_iters=self.init_iters, polytype=self.polytype)

    def calibrate(self, ep: Episode, rho, mask=None, admm_iters=None):
        """Solve with per-direction rho; ``mask`` (K,) in {0,1} excludes
        directions by zeroing their model (static shapes, no recompile).
        Cold start: n_chunks (not J0) sets the solution intervals, so the
        solver's chi2-only init phase runs.

        Large problems route to the host-segmented driver automatically
        (bounded device dispatches; a single fused XLA program running for
        minutes trips device/tunnel watchdogs — solver.solve_admm_host).
        Under a jax trace (the vmapped hint sweep) the fused path is the
        only legal one and is kept.
        """
        C = ep.Ccal
        if mask is not None:
            C = C * jnp.asarray(mask)[None, :, None, None, None]
        traced = any(isinstance(x, jax.core.Tracer)
                     for x in (C, ep.V, rho, admm_iters))
        if not traced and self._use_host_solver(admm_iters):
            return solver.solve_admm_host(
                ep.V, C, ep.obs.freqs, ep.f0, jnp.asarray(rho),
                self._solver_cfg(ep.n_dirs), n_chunks=self.n_chunks,
                admm_iters=None if admm_iters is None else int(admm_iters))
        return solver.solve_admm(
            ep.V, C, ep.obs.freqs, ep.f0, jnp.asarray(rho),
            self._solver_cfg(ep.n_dirs), n_chunks=self.n_chunks,
            admm_iters=None if admm_iters is None else jnp.asarray(admm_iters))

    def _use_host_solver(self, admm_iters=None) -> bool:
        """Proxy for 'one fused solve would run too long on a chip': total
        L-BFGS iterations x per-iteration work, with the per-call ADMM
        iteration override (the demixing action's maxiter) counted, not the
        constructor default.  N=14/Nf=3 training configs stay fused (they
        live inside vmapped sweeps and finish in seconds); LOFAR-scale
        N=62/Nf=8 segments.  SMARTCAL_HOST_SOLVER=0/1 overrides."""
        import os

        override = os.environ.get("SMARTCAL_HOST_SOLVER", "").strip()
        if override in ("0", "1"):
            return override == "1"
        admm = self.admm_iters if admm_iters is None else int(admm_iters)
        total_iters = self.init_iters + admm * self.lbfgs_iters
        work = (self.n_stations ** 2) * self.n_freqs * self.n_times
        # calibration units: N=62/Nf=8 at few iterations (3.7e6) measured
        # ~10s steady on one v5e chip and runs fine; the watchdog bites
        # near ~60-90s (2-3e7).  1e7 =~ 35s leaves margin both ways.
        return total_iters * work > 1e7

    def hint_sweep(self, ep: Episode, rho, masks, admm_iters=None,
                   batch=None):
        """Batched masked calibrations (the exhaustive AIC hint): the
        2^(K-1) configurations run as vmapped batches of ``batch`` masks
        (lax.map over batches bounds memory) instead of the reference's 32
        sequential MPI launches.

        Returns the STOKES-I residual statistic per mask — the same
        get_noise_-style quantity (demixingenv.py:233-252,322) the env
        reward and std_data use, so the hint's AIC residual term is on the
        same scale as the reward the agent is trained on (a full-pol RMS
        here would rescale it against the ksel*N complexity penalty)."""
        masks = jnp.asarray(masks, jnp.float32)
        n = int(masks.shape[0])
        batch = min(self.hint_batch if batch is None else batch, n)
        # One jitted program per (n_dirs, n, batch), with EVERY per-episode
        # value (V, C, freqs, f0, rho, masks, iteration count) as a traced
        # ARGUMENT.  The previous eager lax.map closed over the episode
        # arrays, embedding them as constants — a fresh trace + XLA compile
        # of the multi-minute solver program EVERY episode (and per maxiter
        # value), which dominated hint-arm wall-clock (~2-3 min/episode on
        # the CPU host, vs seconds of actual solve work).
        key = (ep.n_dirs, n, batch)
        fn = self._sweep_fns.get(key)
        if fn is None:
            cfg = self._solver_cfg(ep.n_dirs)
            n_chunks = self.n_chunks
            pad = (-n) % batch

            @jax.jit
            def fn(V, C, freqs, f0, rho_, masks_, iters):
                def one(mask):
                    Cm = C * mask[None, :, None, None, None]
                    res = solver.solve_admm(V, Cm, freqs, f0, rho_, cfg,
                                            n_chunks=n_chunks,
                                            admm_iters=iters)
                    stds = jax.vmap(solver.stokes_i_std)(res.residual)
                    return jnp.sqrt(jnp.mean(stds ** 2))

                if batch == 1:
                    # sequential lanes, no vmap: while_loops keep their
                    # per-lane early exits and cond stays a real branch
                    return jax.lax.map(one, masks_)
                padded = jnp.concatenate(
                    [masks_, jnp.zeros((pad,) + masks_.shape[1:],
                                       masks_.dtype)])
                chunks = padded.reshape(-1, batch, masks_.shape[1])
                return jax.lax.map(jax.vmap(one), chunks).reshape(-1)[:n]

            self._sweep_fns[key] = fn
        iters = self.admm_iters if admm_iters is None else admm_iters
        return fn(ep.V, ep.Ccal, ep.obs.freqs, jnp.asarray(ep.f0),
                  jnp.asarray(rho, jnp.float32), masks,
                  jnp.asarray(iters))

    def influence_image(self, ep: Episode, result: solver.SolveResult,
                        rho, rho_spatial, npix=None):
        """Mean influence dirty image over sub-bands (doinfluence.sh role)."""
        npix = npix or self.npix
        freqs = np.asarray(ep.obs.freqs)
        # polytype matches the solve's consensus basis (the reference
        # hard-codes Bernstein here, analysis_torch.py:104 — a solver/
        # influence mismatch we do not reproduce)
        hadd_all = [influence.consensus_hadd_scalars(
            rho, rho_spatial, freqs, ep.f0, fi, n_poly=self.n_poly,
            polytype=self.polytype) for fi in range(self.n_freqs)]
        uvw = jnp.asarray(np.asarray(ep.obs.uvw).reshape(-1, 3))
        cell = imager.default_cell(ep.obs.uvw, float(freqs[-1]))
        imgs = []
        for fi in range(self.n_freqs):
            Rk = solver.residual_to_kernel(result.residual[fi])
            inf = influence.influence_visibilities(
                Rk, ep.Ccal[fi], result.J[fi], hadd_all[fi],
                self.n_stations, self.n_chunks)
            ivis = influence.stokes_i_influence(inf.vis)
            imgs.append(imager.dirty_image_sr(uvw, ivis, float(freqs[fi]),
                                              cell, npix=npix))
        return jnp.mean(jnp.stack(imgs), axis=0)

    def data_image(self, ep: Episode, npix=None):
        cell = imager.default_cell(ep.obs.uvw,
                                   float(np.asarray(ep.obs.freqs)[-1]))
        return imager.multifreq_image_sr(ep.obs.uvw, ep.V, ep.obs.freqs,
                                         cell, npix=npix or self.npix)

    def residual_image(self, ep: Episode, result: solver.SolveResult,
                       npix=None):
        cell = imager.default_cell(ep.obs.uvw,
                                   float(np.asarray(ep.obs.freqs)[-1]))
        return imager.multifreq_image_sr(ep.obs.uvw, result.residual,
                                         ep.obs.freqs, cell,
                                         npix=npix or self.npix)

    def noise_std(self, V):
        """sqrt(mean_f std(Stokes I)^2) — the reference's get_noise_
        (demixingenv.py:233-252) over MS columns."""
        stds = jax.vmap(solver.stokes_i_std)(V)
        return jnp.sqrt(jnp.mean(stds ** 2))
