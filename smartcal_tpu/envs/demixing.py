"""DemixingEnv: RL environment for selecting demixing directions.

Parity target: ``demixing_rl/demixingenv.py`` — action = K values in
[-1, 1]: K-1 direction-selection probabilities (select when the [0,1] map
exceeds 0.5, :113-118) plus one max-ADMM-iterations value scaled to
[5, 30] (:111); observation = {influence map (zeros unless
``provide_influence``), metadata 3K+2 = separations/azimuth/elevation per
direction (deg) + log(f_low_MHz) + N_stations, selected directions' sep
zeroed} (:144-146, :197-203); reward = -AIC normalized by the empirical
(-859)/3559 minus maxiter/100, relative to the single-direction baseline
``reward0`` (:338-355); hint = exhaustive sweep over all 2^(K-1) subsets,
AIC -> softmin(tau=100) -> expected selection vector (:301-336).

The hint sweep — 32 sequential MPI calibrations in the reference — is one
batched masked solve here (radio.RadioBackend.hint_sweep).
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from smartcal_tpu import obs
from smartcal_tpu.envs import radio

LOW, HIGH = 0.0, 1.0
LOW_ITER, HIGH_ITER = 5, 30     # demixingenv.py:27-28
INF_SCALE = 1e-3
META_SCALE = 1e-3
EPS = 0.01
REWARD_MEAN, REWARD_STD = -859.0, 3559.0   # demixingenv.py:349-350


def scalar_to_kvec(n, K=5):
    """Integer -> K binary selection bits (demixingenv.py:297-303)."""
    ll = [1 if digit == "1" else 0 for digit in bin(n)[2:]]
    a = np.zeros(K)
    a[len(a) - len(ll):] = ll
    return a


class DemixingEnv:
    """Gym-style env, dict observations {'infmap', 'metadata'}."""

    def __init__(self, K=6, provide_hint=False, provide_influence=False,
                 backend: Optional[radio.RadioBackend] = None, seed=0,
                 tau=100.0, prefetch=False):
        self.K = K
        self.provide_hint = provide_hint
        self.provide_influence = provide_influence
        self.backend = backend or radio.RadioBackend(admm_iters=30)
        # double-buffered episode construction (see CalibEnv.prefetch)
        self.prefetch = prefetch
        self._pf_tag = None
        self.tau = tau
        self._key = jax.random.PRNGKey(seed)
        self.ep = None
        self.mdl = None
        self.metadata = np.zeros(3 * K + 2, np.float32)
        self.elevation = None
        self.rho = np.ones(K, np.float32)
        self.maxiter = 10
        self.std_data = 1.0
        self.std_residual = 1.0
        self.reward0 = 0.0
        self.hint = None
        self.npix = self.backend.npix

    def _next_key(self):
        self._key, k = jax.random.split(self._key)
        return k

    @property
    def n_actions(self):
        return self.K

    def _mask(self, clus_sel):
        """(K,) mask: selected outliers + always the target (last)."""
        m = np.zeros(self.K, np.float32)
        m[clus_sel] = 1.0
        m[self.K - 1] = 1.0
        return m

    def _calibrate(self, mask):
        res = self.backend.calibrate(self.ep, self.rho, mask=mask,
                                     admm_iters=self.maxiter)
        return res

    def _influence_map(self, res, mask):
        if not self.provide_influence:
            return np.zeros((self.npix, self.npix), np.float32)
        alpha = np.zeros(self.K, np.float32)
        img = self.backend.influence_image(self.ep, res, self.rho * mask
                                           + (1 - mask), alpha)
        return np.asarray(img)

    def calculate_reward_(self, Kselected):
        """-AIC, normalized; penalty grows with maxiter
        (demixingenv.py:338-355)."""
        data_var = self.std_data ** 2
        noise_var = self.std_residual ** 2
        N = self.backend.n_stations
        reward = (-N * N * noise_var / (data_var + EPS)
                  - Kselected * N)
        reward = (reward - REWARD_MEAN) / REWARD_STD
        return reward - self.maxiter / 100.0

    def step(self, action):
        action = np.asarray(action, np.float32).squeeze()
        assert action.shape == (self.K,)
        sel = action[:self.K - 1] * (HIGH - LOW) / 2 + (HIGH + LOW) / 2
        self.maxiter = int(action[self.K - 1]
                           * (HIGH_ITER - LOW_ITER) / 2
                           + (HIGH_ITER + LOW_ITER) / 2)
        clus_sel = np.where(sel > 0.5)[0].tolist()
        mask = self._mask(clus_sel)
        Kselected = int(mask.sum())

        with obs.span("episode_step", env="demix"):
            res = self._calibrate(mask)
            with obs.span("reward"):
                self.std_residual = float(
                    self.backend.noise_std(res.residual))
            infdata = self._influence_map(res, mask)

        md = self.metadata.copy()
        md[np.where(mask > 0)[0]] = 0.0     # separations of calibrated dirs
        observation = {"infmap": infdata * INF_SCALE,
                       "metadata": md * META_SCALE}
        reward = self.calculate_reward_(Kselected) - self.reward0
        done = False
        info = {"sigma_res": self.std_residual}
        if self.provide_hint:
            if self.hint is None:
                self.hint = self.get_hint()
            return observation, reward, done, self.hint, info
        return observation, reward, done, info

    def _prefetch_tag(self, key):
        # namespaced per env INSTANCE (see CalibEnv._prefetch_tag)
        return (f"{type(self).__name__}-{id(self)}-"
                + np.asarray(key).tobytes().hex())

    def reset(self):
        with obs.span("episode_reset", env="demix"):
            return self._reset()

    def _reset(self):
        key = self._next_key()
        got = (self.backend.take_prefetched(self._prefetch_tag(key))
               if self.prefetch else None)
        self.ep, self.mdl = got or self.backend.new_demixing_episode(
            key, self.K)
        if self.prefetch:
            nxt = jax.random.split(self._key)[1]
            self._pf_tag = self._prefetch_tag(nxt)
            self.backend.prefetch_episode(
                self._pf_tag,
                lambda k=nxt: self.backend.new_demixing_episode(k, self.K))
        self.elevation = self.mdl.elevation
        self.rho = self.mdl.rho.astype(np.float32)
        self.maxiter = 10
        mask = self._mask([])               # target only
        res = self._calibrate(mask)
        self.std_data = float(self.backend.noise_std(self.ep.V))
        self.std_residual = float(self.backend.noise_std(res.residual))
        self.reward0 = self.calculate_reward_(1)

        freqs = np.asarray(self.ep.obs.freqs)
        md = np.zeros(3 * self.K + 2, np.float32)
        md[:self.K] = self.mdl.separations
        md[self.K:2 * self.K] = self.mdl.azimuth
        md[2 * self.K:3 * self.K] = self.mdl.elevation
        md[-2] = np.log(freqs[0] / 1e6)
        md[-1] = self.backend.n_stations
        self.metadata = md

        infdata = self._influence_map(res, mask)
        self.hint = None
        return {"infmap": infdata * INF_SCALE,
                "metadata": md * META_SCALE}

    def get_hint(self):
        """Exhaustive AIC sweep -> softmin expectation
        (demixingenv.py:301-336), batched on device.

        ALL 2^(K-1) configurations enter the batched solve at a FIXED lane
        count; low-elevation configs run as target-only lanes whose result
        is discarded (their AIC keeps the reference's fixed 1e5,
        demixingenv.py:311-315).  A variable valid-lane count would change
        the vmapped program's shape per episode and recompile the
        multi-minute solver program for every distinct count — the padded
        static shape compiles once per process (and once ever with the
        persistent cache), which on the single-core host dominates the
        few wasted lanes.
        """
        n_cfg = 2 ** (self.K - 1)
        masks = np.zeros((n_cfg, self.K), np.float32)
        valid = np.zeros(n_cfg, bool)
        AIC = np.full(n_cfg, 1e5)   # low-elevation configs keep the fixed AIC
        for idx in range(n_cfg):
            bits = scalar_to_kvec(idx, self.K - 1)
            chosen_el = self.elevation[:-1][bits > 0]
            if not np.any(chosen_el < 1.0):
                masks[idx] = self._mask(np.where(bits > 0)[0].tolist())
                valid[idx] = True
            else:
                masks[idx] = self._mask([])          # dummy target-only lane
        sigma_res = np.asarray(self.backend.hint_sweep(
            self.ep, self.rho, masks, admm_iters=self.maxiter))

        N = self.backend.n_stations
        for idx in np.where(valid)[0]:
            ksel = int(masks[idx].sum())
            AIC[idx] = ((N * sigma_res[idx] / self.std_data) ** 2
                        + ksel * N)
        probs = np.exp(-AIC / self.tau)
        probs /= probs.sum()
        hint = np.zeros(self.K - 1)
        for idx in range(n_cfg):
            hint += probs[idx] * scalar_to_kvec(idx, self.K - 1)
        hint = (hint - (HIGH + LOW) / 2) * (2 / (HIGH - LOW))
        out = np.zeros(self.K, np.float32)
        out[:self.K - 1] = hint
        out[self.K - 1] = ((self.maxiter - (HIGH_ITER + LOW_ITER) / 2)
                           * (2 / (HIGH_ITER - LOW_ITER)))
        return out

    def render(self, mode="human"):
        obs.echo(f"maxiter {self.maxiter} rho {self.rho}", event="render")

    def close(self):
        if self._pf_tag is not None:
            self.backend.discard_prefetched(self._pf_tag)
            self._pf_tag = None


class BatchedDemixingEnv:
    """``n_envs`` DemixingEnv lanes advanced as ONE batched program.

    Lane ``i`` reproduces ``DemixingEnv(K, seed=seed + i)`` at the
    episode level (independent per-lane key streams; host-side episode
    construction; batched masked solve + reward statistics downstream).
    The per-lane max-ADMM-iterations action rides as a traced (E,)
    argument of the one batched solve — no recompile across maxiter
    draws, exactly like the sequential path's traced ``admm_iters``.

    ``fused=False`` keeps the sequential per-lane route as the parity
    oracle (same flag discipline as BatchedCalibEnv).  The exhaustive
    hint sweep stays a per-lane call (it is already a batched masked
    solve internally — ``RadioBackend.hint_sweep``); ``provide_hint``
    is therefore not vectorized here and raises.
    """

    def __init__(self, K=6, n_envs=4, provide_influence=False,
                 backend: Optional[radio.RadioBackend] = None, seed=0,
                 fused=True):
        self.K = K
        self.n_envs = int(n_envs)
        self.provide_influence = provide_influence
        self.backend = backend or radio.RadioBackend(admm_iters=30)
        self.fused = fused
        self.npix = self.backend.npix
        E = self.n_envs
        self._keys = [jax.random.PRNGKey(seed + i) for i in range(E)]
        self.metadata = np.zeros((E, 3 * K + 2), np.float32)
        self.elevation = [None] * E
        self.rho = np.ones((E, K), np.float32)
        self.maxiter = np.full(E, 10, np.int32)
        self.std_data = np.ones(E, np.float32)
        self.std_residual = np.ones(E, np.float32)
        self.reward0 = np.zeros(E, np.float32)
        self.lane_episode = np.zeros(E, np.int64)
        self.lane_step = np.zeros(E, np.int64)
        self.eps = [None] * E
        self.mdls = [None] * E
        self.bep = None
        self._last_obs = None

    @property
    def n_actions(self):
        return self.K

    def _next_lane_key(self, i):
        self._keys[i], k = jax.random.split(self._keys[i])
        return k

    def _masks(self, sel_rows):
        """(E, K) masks from per-lane selected-outlier index lists (the
        target, lane-wise the LAST direction, is always selected)."""
        m = np.zeros((self.n_envs, self.K), np.float32)
        for i, sel in enumerate(sel_rows):
            m[i, sel] = 1.0
            m[i, self.K - 1] = 1.0
        return m

    def _calibrate(self, masks):
        if self.fused:
            res = self.backend.calibrate_batched(
                self.bep, self.rho, mask=masks, admm_iters=self.maxiter)
            # np.array (not asarray): jax buffers surface read-only and
            # callers assign into the returned statistics in place
            sig = np.array(self.backend.noise_std_batched(res.residual))
            return res, sig
        sigs, residuals = [], []
        for i in range(self.n_envs):
            r = self.backend.calibrate(self.eps[i], self.rho[i],
                                       mask=masks[i],
                                       admm_iters=int(self.maxiter[i]))
            residuals.append(r)
            sigs.append(float(self.backend.noise_std(r.residual)))
        return residuals, np.asarray(sigs, np.float32)

    def _influence_maps(self, res, masks):
        if not self.provide_influence:
            return np.zeros((self.n_envs, self.npix, self.npix),
                            np.float32)
        alpha = np.zeros((self.n_envs, self.K), np.float32)
        rho_eff = self.rho * masks + (1 - masks)
        if self.fused:
            return np.asarray(self.backend.influence_images_batched(
                self.bep, res, rho_eff, alpha))
        return np.stack([np.asarray(self.backend.influence_image(
            self.eps[i], res[i], rho_eff[i], alpha[i]))
            for i in range(self.n_envs)])

    def calculate_rewards(self, Kselected):
        """Vectorized ``DemixingEnv.calculate_reward_`` over lanes."""
        data_var = self.std_data ** 2
        noise_var = self.std_residual ** 2
        N = self.backend.n_stations
        reward = (-N * N * noise_var / (data_var + EPS)
                  - np.asarray(Kselected) * N)
        reward = (reward - REWARD_MEAN) / REWARD_STD
        return (reward - self.maxiter / 100.0).astype(np.float32)

    def reset(self):
        return self.reset_lanes(np.ones(self.n_envs, bool))

    def reset_lanes(self, done):
        done = np.asarray(done, bool)
        with obs.span("episode_reset", env="demix_batched",
                      lanes=int(done.sum())):
            return self._reset_lanes(done)

    def _reset_lanes(self, done):
        for i in np.where(done)[0]:
            key = self._next_lane_key(i)
            self.eps[i], self.mdls[i] = \
                self.backend.new_demixing_episode(key, self.K)
            self.lane_episode[i] += 1
            self.lane_step[i] = 0
            mdl = self.mdls[i]
            self.elevation[i] = mdl.elevation
            self.rho[i] = mdl.rho.astype(np.float32)
            self.maxiter[i] = 10
            freqs = np.asarray(self.eps[i].obs.freqs)
            md = np.zeros(3 * self.K + 2, np.float32)
            md[:self.K] = mdl.separations
            md[self.K:2 * self.K] = mdl.azimuth
            md[2 * self.K:3 * self.K] = mdl.elevation
            md[-2] = np.log(freqs[0] / 1e6)
            md[-1] = self.backend.n_stations
            self.metadata[i] = md
            if self.bep is not None:
                self.bep = self.backend.splice_episode(self.bep, int(i),
                                                       self.eps[i])
        if self.bep is None:
            self.bep = self.backend.stack_episodes(self.eps)

        masks = self._masks([[] for _ in range(self.n_envs)])
        res, sig = self._calibrate(masks)
        self.std_data[done] = np.asarray(
            self.backend.noise_std_batched(self.bep.V))[done]
        self.std_residual[done] = sig[done]
        self.reward0[done] = self.calculate_rewards(
            np.ones(self.n_envs))[done]
        infmaps = self._influence_maps(res, masks)
        new_obs = {"infmap": infmaps * INF_SCALE,
                   "metadata": self.metadata * META_SCALE}
        if self._last_obs is not None:
            keep = ~done
            for k in new_obs:
                new_obs[k][keep] = self._last_obs[k][keep]
        self._last_obs = new_obs
        return new_obs

    def step(self, actions):
        actions = np.asarray(actions, np.float32).reshape(
            self.n_envs, self.K)
        sel = actions[:, :self.K - 1] * (HIGH - LOW) / 2 \
            + (HIGH + LOW) / 2
        self.maxiter = (actions[:, self.K - 1]
                        * (HIGH_ITER - LOW_ITER) / 2
                        + (HIGH_ITER + LOW_ITER) / 2).astype(np.int32)
        sel_rows = [np.where(s > 0.5)[0].tolist() for s in sel]
        masks = self._masks(sel_rows)
        Kselected = masks.sum(axis=1)

        with obs.span("episode_step", env="demix_batched",
                      lanes=self.n_envs):
            res, self.std_residual = self._calibrate(masks)
            infmaps = self._influence_maps(res, masks)
        self.lane_step += 1
        md = self.metadata.copy()
        md[:, :self.K][masks > 0] = 0.0   # separations of calibrated dirs
        observation = {"infmap": infmaps * INF_SCALE,
                       "metadata": md * META_SCALE}
        self._last_obs = observation
        rewards = self.calculate_rewards(Kselected) - self.reward0
        dones = np.zeros(self.n_envs, bool)
        infos = {"sigma_res": self.std_residual.copy()}
        return observation, rewards, dones, infos

    def state_dict(self):
        return {
            "kind": "batched_demix_env",
            "keys": np.stack([np.asarray(k) for k in self._keys]),
            "lane_episode": self.lane_episode.copy(),
            "lane_step": self.lane_step.copy(),
        }

    def load_state_dict(self, state):
        keys = np.asarray(state["keys"])
        assert keys.shape[0] == self.n_envs, \
            f"checkpoint has {keys.shape[0]} lanes, env has {self.n_envs}"
        self._keys = [jnp.asarray(k) for k in keys]
        self.lane_episode = np.asarray(state["lane_episode"]).copy()
        self.lane_step = np.asarray(state["lane_step"]).copy()

    def close(self):
        pass
