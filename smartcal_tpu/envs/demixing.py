"""DemixingEnv: RL environment for selecting demixing directions.

Parity target: ``demixing_rl/demixingenv.py`` — action = K values in
[-1, 1]: K-1 direction-selection probabilities (select when the [0,1] map
exceeds 0.5, :113-118) plus one max-ADMM-iterations value scaled to
[5, 30] (:111); observation = {influence map (zeros unless
``provide_influence``), metadata 3K+2 = separations/azimuth/elevation per
direction (deg) + log(f_low_MHz) + N_stations, selected directions' sep
zeroed} (:144-146, :197-203); reward = -AIC normalized by the empirical
(-859)/3559 minus maxiter/100, relative to the single-direction baseline
``reward0`` (:338-355); hint = exhaustive sweep over all 2^(K-1) subsets,
AIC -> softmin(tau=100) -> expected selection vector (:301-336).

The hint sweep — 32 sequential MPI calibrations in the reference — is one
batched masked solve here (radio.RadioBackend.hint_sweep).
"""

from typing import Optional

import jax
import numpy as np

from smartcal_tpu import obs
from smartcal_tpu.envs import radio

LOW, HIGH = 0.0, 1.0
LOW_ITER, HIGH_ITER = 5, 30     # demixingenv.py:27-28
INF_SCALE = 1e-3
META_SCALE = 1e-3
EPS = 0.01
REWARD_MEAN, REWARD_STD = -859.0, 3559.0   # demixingenv.py:349-350


def scalar_to_kvec(n, K=5):
    """Integer -> K binary selection bits (demixingenv.py:297-303)."""
    ll = [1 if digit == "1" else 0 for digit in bin(n)[2:]]
    a = np.zeros(K)
    a[len(a) - len(ll):] = ll
    return a


class DemixingEnv:
    """Gym-style env, dict observations {'infmap', 'metadata'}."""

    def __init__(self, K=6, provide_hint=False, provide_influence=False,
                 backend: Optional[radio.RadioBackend] = None, seed=0,
                 tau=100.0, prefetch=False):
        self.K = K
        self.provide_hint = provide_hint
        self.provide_influence = provide_influence
        self.backend = backend or radio.RadioBackend(admm_iters=30)
        # double-buffered episode construction (see CalibEnv.prefetch)
        self.prefetch = prefetch
        self._pf_tag = None
        self.tau = tau
        self._key = jax.random.PRNGKey(seed)
        self.ep = None
        self.mdl = None
        self.metadata = np.zeros(3 * K + 2, np.float32)
        self.elevation = None
        self.rho = np.ones(K, np.float32)
        self.maxiter = 10
        self.std_data = 1.0
        self.std_residual = 1.0
        self.reward0 = 0.0
        self.hint = None
        self.npix = self.backend.npix

    def _next_key(self):
        self._key, k = jax.random.split(self._key)
        return k

    @property
    def n_actions(self):
        return self.K

    def _mask(self, clus_sel):
        """(K,) mask: selected outliers + always the target (last)."""
        m = np.zeros(self.K, np.float32)
        m[clus_sel] = 1.0
        m[self.K - 1] = 1.0
        return m

    def _calibrate(self, mask):
        res = self.backend.calibrate(self.ep, self.rho, mask=mask,
                                     admm_iters=self.maxiter)
        return res

    def _influence_map(self, res, mask):
        if not self.provide_influence:
            return np.zeros((self.npix, self.npix), np.float32)
        alpha = np.zeros(self.K, np.float32)
        img = self.backend.influence_image(self.ep, res, self.rho * mask
                                           + (1 - mask), alpha)
        return np.asarray(img)

    def calculate_reward_(self, Kselected):
        """-AIC, normalized; penalty grows with maxiter
        (demixingenv.py:338-355)."""
        data_var = self.std_data ** 2
        noise_var = self.std_residual ** 2
        N = self.backend.n_stations
        reward = (-N * N * noise_var / (data_var + EPS)
                  - Kselected * N)
        reward = (reward - REWARD_MEAN) / REWARD_STD
        return reward - self.maxiter / 100.0

    def step(self, action):
        action = np.asarray(action, np.float32).squeeze()
        assert action.shape == (self.K,)
        sel = action[:self.K - 1] * (HIGH - LOW) / 2 + (HIGH + LOW) / 2
        self.maxiter = int(action[self.K - 1]
                           * (HIGH_ITER - LOW_ITER) / 2
                           + (HIGH_ITER + LOW_ITER) / 2)
        clus_sel = np.where(sel > 0.5)[0].tolist()
        mask = self._mask(clus_sel)
        Kselected = int(mask.sum())

        with obs.span("episode_step", env="demix"):
            res = self._calibrate(mask)
            with obs.span("reward"):
                self.std_residual = float(
                    self.backend.noise_std(res.residual))
            infdata = self._influence_map(res, mask)

        md = self.metadata.copy()
        md[np.where(mask > 0)[0]] = 0.0     # separations of calibrated dirs
        observation = {"infmap": infdata * INF_SCALE,
                       "metadata": md * META_SCALE}
        reward = self.calculate_reward_(Kselected) - self.reward0
        done = False
        info = {"sigma_res": self.std_residual}
        if self.provide_hint:
            if self.hint is None:
                self.hint = self.get_hint()
            return observation, reward, done, self.hint, info
        return observation, reward, done, info

    def _prefetch_tag(self, key):
        # namespaced per env INSTANCE (see CalibEnv._prefetch_tag)
        return (f"{type(self).__name__}-{id(self)}-"
                + np.asarray(key).tobytes().hex())

    def reset(self):
        with obs.span("episode_reset", env="demix"):
            return self._reset()

    def _reset(self):
        key = self._next_key()
        got = (self.backend.take_prefetched(self._prefetch_tag(key))
               if self.prefetch else None)
        self.ep, self.mdl = got or self.backend.new_demixing_episode(
            key, self.K)
        if self.prefetch:
            nxt = jax.random.split(self._key)[1]
            self._pf_tag = self._prefetch_tag(nxt)
            self.backend.prefetch_episode(
                self._pf_tag,
                lambda k=nxt: self.backend.new_demixing_episode(k, self.K))
        self.elevation = self.mdl.elevation
        self.rho = self.mdl.rho.astype(np.float32)
        self.maxiter = 10
        mask = self._mask([])               # target only
        res = self._calibrate(mask)
        self.std_data = float(self.backend.noise_std(self.ep.V))
        self.std_residual = float(self.backend.noise_std(res.residual))
        self.reward0 = self.calculate_reward_(1)

        freqs = np.asarray(self.ep.obs.freqs)
        md = np.zeros(3 * self.K + 2, np.float32)
        md[:self.K] = self.mdl.separations
        md[self.K:2 * self.K] = self.mdl.azimuth
        md[2 * self.K:3 * self.K] = self.mdl.elevation
        md[-2] = np.log(freqs[0] / 1e6)
        md[-1] = self.backend.n_stations
        self.metadata = md

        infdata = self._influence_map(res, mask)
        self.hint = None
        return {"infmap": infdata * INF_SCALE,
                "metadata": md * META_SCALE}

    def get_hint(self):
        """Exhaustive AIC sweep -> softmin expectation
        (demixingenv.py:301-336), batched on device.

        ALL 2^(K-1) configurations enter the batched solve at a FIXED lane
        count; low-elevation configs run as target-only lanes whose result
        is discarded (their AIC keeps the reference's fixed 1e5,
        demixingenv.py:311-315).  A variable valid-lane count would change
        the vmapped program's shape per episode and recompile the
        multi-minute solver program for every distinct count — the padded
        static shape compiles once per process (and once ever with the
        persistent cache), which on the single-core host dominates the
        few wasted lanes.
        """
        n_cfg = 2 ** (self.K - 1)
        masks = np.zeros((n_cfg, self.K), np.float32)
        valid = np.zeros(n_cfg, bool)
        AIC = np.full(n_cfg, 1e5)   # low-elevation configs keep the fixed AIC
        for idx in range(n_cfg):
            bits = scalar_to_kvec(idx, self.K - 1)
            chosen_el = self.elevation[:-1][bits > 0]
            if not np.any(chosen_el < 1.0):
                masks[idx] = self._mask(np.where(bits > 0)[0].tolist())
                valid[idx] = True
            else:
                masks[idx] = self._mask([])          # dummy target-only lane
        sigma_res = np.asarray(self.backend.hint_sweep(
            self.ep, self.rho, masks, admm_iters=self.maxiter))

        N = self.backend.n_stations
        for idx in np.where(valid)[0]:
            ksel = int(masks[idx].sum())
            AIC[idx] = ((N * sigma_res[idx] / self.std_data) ** 2
                        + ksel * N)
        probs = np.exp(-AIC / self.tau)
        probs /= probs.sum()
        hint = np.zeros(self.K - 1)
        for idx in range(n_cfg):
            hint += probs[idx] * scalar_to_kvec(idx, self.K - 1)
        hint = (hint - (HIGH + LOW) / 2) * (2 / (HIGH - LOW))
        out = np.zeros(self.K, np.float32)
        out[:self.K - 1] = hint
        out[self.K - 1] = ((self.maxiter - (HIGH_ITER + LOW_ITER) / 2)
                           * (2 / (HIGH_ITER - LOW_ITER)))
        return out

    def render(self, mode="human"):
        obs.echo(f"maxiter {self.maxiter} rho {self.rho}", event="render")

    def close(self):
        if self._pf_tag is not None:
            self.backend.discard_prefetched(self._pf_tag)
            self._pf_tag = None
