"""Elastic-net hyperparameter-tuning environment, fully on-device.

Re-expresses the reference ``elasticnet/enetenv.py`` gym env as pure
``(reset, step, hint)`` functions so an entire episode — inner L-BFGS solve,
influence eigen-state, reward — jit-compiles into one XLA computation and can
be scanned/vmapped/sharded.  Semantics follow the reference line by line:

* problem: ``min_x ||y - Ax||^2 + rho0 ||x||_2^2 + rho1 ||x||_1``
  (``enetenv.py:27-28``); action -> rho affine map with out-of-range penalty
  (``:75-84``); per-step fresh noise at fixed SNR (``:87-90``);
* inner solve: 20 epochs x ``LBFGSNew(max_iter=10, history_size=7)``
  (``:101-114``) -> here one :func:`lbfgs_solve` with ``max_iters=200``;
* influence state (``:117-139``): model Jacobian, mixed derivative
  d(dL/dx)/dy, per-column inverse-Hessian product reusing the L-BFGS
  curvature history, ``B = jac @ invH @ d2L``, state = 1 + Re(eig(B));
* reward ``||y||/||Ax-y|| + min(E)/max(E) + penalty`` (``:149``);
* reset redraws A and a sparse ground truth with ``Mo ~ U{3..M-1}`` nonzeros
  at (possibly colliding) random indices (``:163-183``);
* hint: 5x5 grid search over (lambda1, lambda2) with 2-fold cross-validation
  (sklearn ``GridSearchCV(cv=2)`` in the reference, ``:229-241``) — here the
  25 candidate x 2 fold solves run as one ``vmap`` on device.

Eigen-state on TPU: nonsymmetric ``eig`` is host-only in XLA.  The exact
``B = jac . H^{-1} . (-2 A^T)`` is a product of symmetric matrices when
``H^{-1}`` is exact (``H = 2 A^T A + 2 rho0 I`` a.e.), so its spectrum is
real and equals that of the symmetrised ``(B + B^T)/2`` up to the (small)
asymmetry of the BFGS approximation.  Default ``eig_mode='symmetric'`` uses
``eigvalsh`` on-device; ``eig_mode='exact'`` calls host ``numpy.linalg.eigvals``
through ``pure_callback`` for bit-parity studies.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.lbfgs import lbfgs_solve, inv_hessian_mult

LOW = 1e-3   # enetenv.py:21
HIGH = 1e-1  # enetenv.py:22
HINT_GRID = (0.001, 0.005, 0.01, 0.05, 0.1)  # enetenv.py:233


@dataclasses.dataclass(frozen=True)
class EnetConfig:
    M: int = 20                  # parameters (columns)
    N: int = 20                  # data points (rows)
    snr: float = 0.1             # ||noise||/||data|| (enetenv.py:48)
    lbfgs_iters: int = 200       # 20 epochs x max_iter 10 (enetenv.py:101-114)
    history_size: int = 7
    eig_mode: str = "symmetric"  # 'symmetric' | 'exact'

    @property
    def obs_dim(self) -> int:
        # state vector = concat(eig (N), A.ravel() (N*M)) — enet_sac.py:40
        return self.N + self.N * self.M


class EnetState(NamedTuple):
    A: jnp.ndarray    # (N, M) normalised design matrix
    x0: jnp.ndarray   # (M,) sparse ground truth
    y0: jnp.ndarray   # (N,) noise-free data
    y: jnp.ndarray    # (N,) last noisy draw
    x: jnp.ndarray    # (M,) last solution (render/eval)


def reset(cfg: EnetConfig, key) -> Tuple[EnetState, jnp.ndarray]:
    """Draw a new problem (enetenv.py:163-183)."""
    kA, kMo, kz, kidx = jax.random.split(key, 4)
    M, N = cfg.M, cfg.N
    A = jax.random.normal(kA, (N, M), jnp.float32)
    A = A / jnp.linalg.norm(A)

    Mo = jax.random.randint(kMo, (), 3, M)          # nnz count, U{3..M-1}
    z = jax.random.normal(kz, (M,), jnp.float32)
    idx = jax.random.randint(kidx, (M,), 0, M)
    # only the first Mo draws land; the rest scatter out of bounds (dropped),
    # duplicates overwrite — same distribution as x0[randint(0,M,Mo)]=z0
    idx_eff = jnp.where(jnp.arange(M) < Mo, idx, M)
    x0 = jnp.zeros((M,), jnp.float32).at[idx_eff].set(z, mode="drop")

    y0 = A @ x0
    st = EnetState(A=A, x0=x0, y0=y0, y=y0, x=jnp.zeros((M,), jnp.float32))
    obs = jnp.concatenate([jnp.zeros((N,), jnp.float32), A.ravel()])
    return st, obs


def action_to_rho(action):
    """Affine action->(rho, penalty) map (enetenv.py:75-84): actions in
    [-1, 1] span [LOW, HIGH]; out-of-range components are clamped with a
    -0.1 penalty each."""
    rho_raw = action * (HIGH - LOW) / 2.0 + (HIGH + LOW) / 2.0
    penalty = (-0.1 * jnp.sum(rho_raw < LOW)
               - 0.1 * jnp.sum(rho_raw > HIGH)).astype(jnp.float32)
    return jnp.clip(rho_raw, LOW, HIGH), penalty


def _eig_state(cfg: EnetConfig, B: jnp.ndarray) -> jnp.ndarray:
    if cfg.eig_mode == "exact":
        def host_eig(b):
            return np.real(np.linalg.eigvals(np.asarray(b))).astype(np.float32)

        E = jax.pure_callback(
            host_eig, jax.ShapeDtypeStruct((cfg.N,), jnp.float32), B,
            vmap_method="sequential")
    else:
        E = jnp.linalg.eigvalsh(0.5 * (B + B.T))
    return 1.0 + E


def _solve_and_influence(cfg: EnetConfig, A, y, rho):
    """Inner solve + influence eigen-state (enetenv.py:96-139)."""
    M = cfg.M

    def lossfn(x, yv):
        err = yv - A @ x
        return (jnp.sum(err ** 2) + rho[0] * jnp.sum(x ** 2)
                + rho[1] * jnp.sum(jnp.abs(x)))

    res = lbfgs_solve(lambda x: lossfn(x, y), jnp.zeros((M,), jnp.float32),
                      max_iters=cfg.lbfgs_iters,
                      history_size=cfg.history_size)
    x = res.x

    # Jacobian of the model A@x w.r.t. x is A (reference computes it row by
    # row via backward(), enetenv.py:118 — it is exactly A)
    jac = A
    # d(dL/dx)/dy — constant in y for this loss; autodiff keeps generality
    # (reference evaluates it at y=ones for the same reason, enetenv.py:121-124)
    ll = jax.jacrev(lambda yv: jax.grad(lossfn)(x, yv))(jnp.ones_like(y))
    mm = jax.vmap(lambda col: inv_hessian_mult(res.hist, col),
                  in_axes=1, out_axes=1)(ll)
    B = jac @ mm
    EE = _eig_state(cfg, B)
    return x, EE


def step(cfg: EnetConfig, st: EnetState, action, key,
         keepnoise: bool = False):
    """One env step (enetenv.py:72-161).

    Returns ``(new_state, obs, reward, done)``; ``done`` is always False as in
    the reference (episode length is driver-limited).
    """
    action = jnp.asarray(action, jnp.float32).reshape(-1)
    rho, penalty = action_to_rho(action)

    n = jax.random.normal(key, (cfg.N,), jnp.float32)
    y_fresh = st.y0 + cfg.snr * jnp.linalg.norm(st.y0) / jnp.linalg.norm(n) * n
    # keepnoise may be a python bool or a traced bool (fused episode loops
    # keep the first step's draw so the cached hint matches its data)
    y = jnp.where(jnp.asarray(keepnoise), st.y, y_fresh)

    x, EE = _solve_and_influence(cfg, st.A, y, rho)

    obs = jnp.concatenate([EE, st.A.ravel()])
    final_err = jnp.linalg.norm(st.A @ x - y)
    reward = (jnp.linalg.norm(y) / final_err
              + jnp.min(EE) / jnp.max(EE) + penalty)

    new_st = st._replace(y=y, x=x)
    return new_st, obs, reward, jnp.asarray(False)


def draw_noise(cfg: EnetConfig, st: EnetState, key) -> EnetState:
    """Draw one noisy observation into ``st.y`` (reference ``initsol``'s data
    draw, enetenv.py:197-202) for subsequent ``keepnoise=True`` steps."""
    n = jax.random.normal(key, (cfg.N,), jnp.float32)
    y = st.y0 + cfg.snr * jnp.linalg.norm(st.y0) / jnp.linalg.norm(n) * n
    return st._replace(y=y)


def get_hint(cfg: EnetConfig, st: EnetState) -> jnp.ndarray:
    """Grid-search hint mapped back to action space (enetenv.py:229-241).

    2-fold CV over the 5x5 lambda grid: sklearn ``KFold(2)`` splits the rows
    into first/second half; each candidate trains on one half (L-BFGS solve of
    the elastic net, as ``SKEnet.fit`` does with scipy L-BFGS-B,
    ``enetenv.py:263-288``) and scores neg-MSE on the other.  All 50 solves
    run as one vmap.
    """
    N = cfg.N
    half = N // 2
    grid = jnp.asarray(
        [(l1, l2) for l1 in HINT_GRID for l2 in HINT_GRID], jnp.float32)

    fold_test = jnp.stack([
        jnp.arange(N) < half,      # fold 0: first half tests
        jnp.arange(N) >= half,     # fold 1: second half tests
    ])

    def cv_mse(lams, test_mask):
        l1, l2 = lams[0], lams[1]
        w = jnp.where(test_mask, 0.0, 1.0)  # train on the complement

        def fun(xv):
            err = (st.y - st.A @ xv) * w
            # SKEnet objective (enetenv.py:275-280): lambda1 multiplies the
            # L1 term, lambda2 the squared L2 term
            return (jnp.sum(err ** 2) + l2 * jnp.sum(xv ** 2)
                    + l1 * jnp.sum(jnp.abs(xv)))

        res = lbfgs_solve(fun, jnp.zeros((cfg.M,), jnp.float32),
                          max_iters=100, history_size=cfg.history_size)
        pred_err = (st.A @ res.x - st.y) ** 2
        return jnp.sum(pred_err * test_mask) / jnp.sum(test_mask)

    mses = jax.vmap(lambda lams: jax.vmap(
        lambda mask: cv_mse(lams, mask))(fold_test))(grid)
    best = jnp.argmin(jnp.mean(mses, axis=1))
    lam = grid[best]
    # inverse of the step() affine map; hint_[0]=lambda1, hint_[1]=lambda2
    return (lam - (HIGH + LOW) / 2.0) / ((HIGH - LOW) / 2.0)


class EnetEnv:
    """Host-driven gym-like wrapper (reference ``ENetEnv`` interface)."""

    def __init__(self, M: int = 20, N: int = 20, provide_hint: bool = False,
                 seed: int = 0, eig_mode: str = "symmetric",
                 lbfgs_iters: int = 200):
        self.cfg = EnetConfig(M=M, N=N, eig_mode=eig_mode,
                              lbfgs_iters=lbfgs_iters)
        self.provide_hint = provide_hint
        self.key = jax.random.PRNGKey(seed)
        self._reset = jax.jit(lambda k: reset(self.cfg, k))
        self._step = jax.jit(
            lambda st, a, k: step(self.cfg, st, a, k))
        self._step_keep = jax.jit(
            lambda st, a, k: step(self.cfg, st, a, k, keepnoise=True))
        self._hint = jax.jit(lambda st: get_hint(self.cfg, st))
        self.state: EnetState = None
        self.hint = None

    def _next_key(self):
        self.key, sub = jax.random.split(self.key)
        return sub

    def reset(self):
        self.state, obs = self._reset(self._next_key())
        self.hint = None
        return jax.device_get(obs)

    def initsol(self):
        """Fix the noise draw for subsequent ``step(..., keepnoise=True)``."""
        self.state = draw_noise(self.cfg, self.state, self._next_key())

    def step(self, action, keepnoise: bool = False):
        step_fn = self._step_keep if keepnoise else self._step
        self.state, obs, reward, done = step_fn(
            self.state, jnp.asarray(action), self._next_key())
        out = (jax.device_get(obs), float(reward), bool(done))
        if self.provide_hint:
            if self.hint is None:
                self.hint = jax.device_get(self._hint(self.state))
            return (*out, self.hint, {})
        return (*out, {})

    def get_hint(self):
        return jax.device_get(self._hint(self.state))
