from .calib import BatchedCalibEnv, CalibEnv  # noqa: F401
from .demixing import BatchedDemixingEnv, DemixingEnv  # noqa: F401
from .demixing_fuzzy import FuzzyDemixingEnv  # noqa: F401
from .enet import EnetConfig, EnetEnv, EnetState  # noqa: F401
from .enet import get_hint as enet_get_hint  # noqa: F401
from .enet import reset as enet_reset  # noqa: F401
from .enet import step as enet_step  # noqa: F401
from .radio import BatchedEpisode, Episode, RadioBackend  # noqa: F401
