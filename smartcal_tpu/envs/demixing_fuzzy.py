"""Fuzzy-controller demixing environment.

Parity target: ``demixing_fuzzy/demixingenv.py`` — same observation/
calibration skeleton as the RL DemixingEnv, but the action parameterizes a
trapezoidal fuzzy controller (models/fuzzy.py): 24 membership values per
outlier + 8 shared target values, mapped from [-1, 1] to [0, 1] (:108-118).
Per outlier the controller scores a priority from (azimuth, azimuth_target,
elevation, elevation_target, separation, log flux, flux ratio); directions
with priority >= the 'high' cutoff are selected (:119-137).  maxiter is
fixed at 15 (:246).  Metadata is 5K+2: sep/az/el + log-fluxes + selection
flags + log(f_low) + N (:55-59, :219-230).  Hint = the default fuzzy
config inverted to action space (:323-332).
"""

from typing import Optional

import numpy as np

from smartcal_tpu import obs as smartcal_obs
from smartcal_tpu.envs import radio
from smartcal_tpu.envs.demixing import DemixingEnv
from smartcal_tpu.models.fuzzy import N_ACTION, DemixController

INF_SCALE = 1e-3
META_SCALE = 1e-3


class FuzzyDemixingEnv(DemixingEnv):
    """Extends the RL demixing env with the fuzzy action parameterization."""

    def __init__(self, K=6, provide_hint=False, provide_influence=False,
                 backend: Optional[radio.RadioBackend] = None, seed=0):
        super().__init__(K=K, provide_hint=provide_hint,
                         provide_influence=provide_influence,
                         backend=backend, seed=seed)
        self.n_fuzzy = N_ACTION
        self.ctrl = DemixController(n_action=self.n_fuzzy)
        self.log_fluxes = None
        self.target_flux = 1.0
        self.maxiter = 15

    @property
    def n_actions(self):
        return 24 * (self.K - 1) + 8

    @property
    def n_metadata(self):
        return 5 * self.K + 2

    def _metadata_vec(self, selected_flags):
        md = np.zeros(self.n_metadata, np.float32)
        md[:self.K] = self.mdl.separations
        md[self.K:2 * self.K] = self.mdl.azimuth
        md[2 * self.K:3 * self.K] = self.mdl.elevation
        md[3 * self.K:4 * self.K] = self.log_fluxes
        md[4 * self.K:5 * self.K] = selected_flags
        freqs = np.asarray(self.ep.obs.freqs)
        md[-2] = np.log(freqs[0] / 1e6)
        md[-1] = self.backend.n_stations
        return md

    def step(self, action):
        action = np.asarray(action, np.float32).squeeze()
        assert action.shape == (self.n_actions,)
        a01 = action * 0.5 + 0.5
        flux_ratio = np.exp(self.log_fluxes) / self.target_flux
        azim, elev, sep = (self.mdl.azimuth, self.mdl.elevation,
                           self.mdl.separations)
        priority = np.zeros(self.K - 1)
        cutoff = np.zeros(self.K - 1)
        for nd in range(self.K - 1):
            a = np.zeros(self.n_fuzzy)
            a[:24] = a01[nd * 24:(nd + 1) * 24]
            a[-8:] = a01[-8:]
            self.ctrl.update_limits(a)
            self.ctrl.create_controller()
            priority[nd] = self.ctrl.evaluate(
                azim[nd], azim[-1], elev[nd], elev[-1], sep[nd],
                self.log_fluxes[nd], flux_ratio[nd])
            cutoff[nd] = self.ctrl.get_high_priority()

        clus_sel = np.where(priority >= cutoff)[0].tolist()
        mask = self._mask(clus_sel)
        Kselected = int(mask.sum())
        self.maxiter = 15
        with smartcal_obs.span("episode_step", env="demix_fuzzy"):
            res = self._calibrate(mask)
            with smartcal_obs.span("reward"):
                self.std_residual = float(
                    self.backend.noise_std(res.residual))
            infdata = self._influence_map(res, mask)

        flags = np.zeros(self.K, np.float32)
        flags[np.where(mask > 0)[0]] = 1.0
        md = self._metadata_vec(flags)
        obs = {"infmap": infdata * INF_SCALE, "metadata": md * META_SCALE}
        reward = self.calculate_reward_(Kselected) - self.reward0
        info = {"priority": priority, "selected": clus_sel}
        if self.provide_hint:
            if self.hint is None:
                self.hint = self.get_hint()
            return obs, reward, False, self.hint, info
        return obs, reward, False, info

    def calculate_reward_(self, Kselected):
        """Fuzzy variant drops the maxiter penalty
        (demixing_fuzzy/demixingenv.py:344-350)."""
        base = super().calculate_reward_(Kselected)
        return base + self.maxiter / 100.0

    def reset(self):
        # run the shared episode setup (fills self.mdl/self.ep/reward0)
        self.ctrl = DemixController(n_action=self.n_fuzzy)
        obs = super().reset()
        self.maxiter = 15       # fuzzy reset value (demixingenv.py:246)
        # K values (target last); per-outlier slices use [:K-1]
        self.log_fluxes = np.log(np.maximum(self.mdl.fluxes, 1e-12))
        self.target_flux = float(max(self.mdl.fluxes[-1], 1e-12))
        flags = np.zeros(self.K, np.float32)
        flags[-1] = 1.0
        md = self._metadata_vec(flags)
        self.metadata = md
        self.hint = self.get_hint() if self.provide_hint else None
        return {"infmap": obs["infmap"], "metadata": md * META_SCALE}

    def get_hint(self):
        """Default fuzzy config as the action (demixing_fuzzy
        demixingenv.py:323-332)."""
        hint_full = np.zeros(self.n_actions)
        hint = DemixController(self.n_fuzzy).update_action()
        for nd in range(self.K - 1):
            hint_full[24 * nd:24 * (nd + 1)] = hint[:24]
        hint_full[-8:] = hint[-8:]
        return (2.0 * (hint_full - 0.5)).astype(np.float32)
