"""Takagi-Sugeno-Kang (TSK) order-1 fuzzy regressor in pure JAX.

Parity target: the pytsk-based model of ``demixing_rl/train_tsk.py``:
Gaussian-membership antecedents (``AntecedentGMF``), ``n_rule`` rules,
order-1 consequents, tanh output head, plus the two custom regularizers —
the inverse-center-distance loss (train_tsk.py:81-98, pushes rule centers
apart) and the sigma-magnitude loss (:100-110).

Model: for input x (M,), rule firing uses log-Gaussian memberships
  z_r = sum_m -(x_m - c_{m,r})^2 / (2 sigma_{m,r}^2)
  w = softmax(z)                         (normalized firing strengths)
  y = tanh( sum_r w_r (A_r x + b_r) )    (order-1 consequents)
"""

import pickle
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


class TSKParams(NamedTuple):
    center: jnp.ndarray   # (M, R)
    sigma: jnp.ndarray    # (M, R)
    A: jnp.ndarray        # (R, M, out)
    b: jnp.ndarray        # (R, out)


def tsk_init(key, n_inputs, n_outputs, n_rule=3, x_sample=None):
    """Init centers from data samples when given (pytsk uses k-means over
    the training inputs; random data draws are the cheap equivalent)."""
    kc, ka, kb = jax.random.split(key, 3)
    if x_sample is not None and x_sample.shape[0] >= n_rule:
        idx = jax.random.choice(kc, x_sample.shape[0], (n_rule,),
                                replace=False)
        center = jnp.asarray(x_sample)[idx].T            # (M, R)
    else:
        center = jax.random.normal(kc, (n_inputs, n_rule))
    sigma = jnp.ones((n_inputs, n_rule))
    A = 0.01 * jax.random.normal(ka, (n_rule, n_inputs, n_outputs))
    b = 0.01 * jax.random.normal(kb, (n_rule, n_outputs))
    return TSKParams(center=center, sigma=sigma, A=A, b=b)


def tsk_forward(params: TSKParams, x):
    """x (..., M) -> (..., out)."""
    d = x[..., :, None] - params.center                  # (..., M, R)
    z = -0.5 * jnp.sum((d / (params.sigma + 1e-8)) ** 2, axis=-2)
    w = jax.nn.softmax(z, axis=-1)                       # (..., R)
    rule_out = jnp.einsum("...m,rmo->...ro", x, params.A) + params.b
    return jnp.tanh(jnp.einsum("...r,...ro->...o", w, rule_out))


def center_difference_loss(params: TSKParams):
    """Inverse pairwise center distance (train_tsk.py:81-98)."""
    c = params.center                                    # (M, R)
    M, R = c.shape
    d2 = (c[:, :, None] - c[:, None, :]) ** 2            # (M, R, R)
    iu = jnp.triu_indices(R, 1)
    inv = jnp.sum(1.0 / (d2[:, iu[0], iu[1]] + 1e-5))
    return inv / (M * R * (R - 1) / 2)


def sigma_loss(params: TSKParams):
    """Mean sigma^2 (train_tsk.py:100-110)."""
    return jnp.mean(params.sigma ** 2)


def tsk_loss(params: TSKParams, x, y, g1=1e-4, g2=1e-4):
    """||y - f(x)||^2 / batch + g1*center_diff + g2*sigma
    (train_tsk.py:136-147)."""
    pred = tsk_forward(params, x)
    mse = jnp.sum((pred - y) ** 2) / x.shape[0]
    return mse + g1 * center_difference_loss(params) + g2 * sigma_loss(params)


def train_tsk(key, x_train, y_train, n_rule=3, n_iter=2000, batch_size=256,
              lr=1e-3, g1=1e-4, g2=1e-4, x_test=None, y_test=None,
              log_every=0):
    """Adam training loop (train_tsk.py:112-158), jit-scanned on device."""
    x_train = jnp.asarray(x_train, jnp.float32)
    y_train = jnp.asarray(y_train, jnp.float32)
    kp, kloop = jax.random.split(key)
    params = tsk_init(kp, x_train.shape[1], y_train.shape[1], n_rule,
                      x_sample=x_train)
    opt = optax.adam(lr)
    opt_state = opt.init(params)
    bs = min(batch_size, x_train.shape[0])

    @jax.jit
    def step(carry, k):
        params, opt_state = carry
        idx = jax.random.choice(k, x_train.shape[0], (bs,), replace=False)
        loss, grads = jax.value_and_grad(tsk_loss)(params, x_train[idx],
                                                   y_train[idx], g1, g2)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    keys = jax.random.split(kloop, n_iter)
    (params, _), losses = jax.lax.scan(step, (params, opt_state), keys)
    out = {"params": params, "losses": np.asarray(losses)}
    if x_test is not None:
        pred = tsk_forward(params, jnp.asarray(x_test))
        out["test_mse"] = float(jnp.mean(jnp.sum(
            (pred - jnp.asarray(y_test)) ** 2, axis=-1)))
    return out


def save_tsk(params: TSKParams, path="tsk.model.pkl"):
    with open(path, "wb") as fh:
        pickle.dump(jax.device_get(params), fh)


def load_tsk(path="tsk.model.pkl") -> TSKParams:
    from smartcal_tpu.runtime.atomic import strict_pickle_load

    return TSKParams(*strict_pickle_load(path))
