"""Transformer classifier for demixing-direction recommendation.

Parity target: ``calibration/transformer_models.py:76-186`` — a 1-layer
encoder whose multi-head attention has NO sequence axis: the input
(batch, K*(Npix^2+8)) is projected to model_dim, reshaped into
``num_heads = K`` head slots, and attention runs ACROSS THE HEADS (each
head is one sky direction; attn_logits are (batch, heads, heads)).
Output is a sigmoid over K-1 labels ("demix this direction?").

Also the generic x/y ReplayBuffer of transformer_models.py:10-70 (host
numpy with ``resize``).
"""

import pickle
from typing import Tuple

import jax.numpy as jnp
import numpy as np
from flax import linen as nn


class HeadAttention(nn.Module):
    """The reference's seq-free MultiheadAttention
    (transformer_models.py:85-119): qkv projection, heads as the attention
    axis, output projection."""

    embed_dim: int
    num_heads: int

    @nn.compact
    def __call__(self, x, return_attention=False):
        head_dim = self.embed_dim // self.num_heads
        qkv = nn.Dense(3 * self.embed_dim,
                       kernel_init=nn.initializers.xavier_uniform(),
                       bias_init=nn.initializers.zeros)(x)
        qkv = qkv.reshape(x.shape[0], self.num_heads, 3 * head_dim)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        logits = jnp.einsum("bhd,bgd->bhg", q, k) / jnp.sqrt(head_dim)
        attn = nn.softmax(logits, axis=-1)
        values = jnp.einsum("bhg,bgd->bhd", attn, v)
        o = nn.Dense(self.embed_dim,
                     kernel_init=nn.initializers.xavier_uniform(),
                     bias_init=nn.initializers.zeros)(
            values.reshape(x.shape[0], self.embed_dim))
        if return_attention:
            return o, attn
        return o


class EncoderBlock(nn.Module):
    """Pre-norm-free residual block (transformer_models.py:121-151)."""

    input_dim: int
    num_heads: int
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, train=False):
        attn_out = HeadAttention(self.input_dim, self.num_heads)(x)
        x = nn.LayerNorm()(x + nn.Dropout(self.dropout,
                                          deterministic=not train)(attn_out))
        h = nn.Dense(self.input_dim)(x)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        h = nn.relu(h)
        h = nn.Dense(self.input_dim)(h)
        x = nn.LayerNorm()(x + nn.Dropout(self.dropout,
                                          deterministic=not train)(h))
        return x


class TransformerEncoder(nn.Module):
    """transformer_models.py:153-186; sigmoid multi-label output."""

    num_layers: int
    input_dim: int
    model_dim: int
    num_classes: int
    num_heads: int
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, train=False):
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.Dense(self.model_dim)(x)
        for _ in range(self.num_layers):
            x = EncoderBlock(self.model_dim, self.num_heads,
                             self.dropout)(x, train=train)
        x = nn.Dense(self.model_dim)(x)
        x = nn.LayerNorm()(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.Dense(self.num_classes)(x)
        return nn.sigmoid(x)


class XYBuffer:
    """Generic (x, y) training buffer with grow-on-demand ``resize``
    (transformer_models.py:10-70) and whole-object pickling."""

    def __init__(self, max_size: int, x_shape: Tuple[int, ...],
                 y_shape: Tuple[int, ...]):
        self.mem_size = max_size
        self.mem_cntr = 0
        self.x = np.zeros((max_size,) + tuple(x_shape), np.float32)
        self.y = np.zeros((max_size,) + tuple(y_shape), np.float32)

    def store(self, x, y):
        i = self.mem_cntr % self.mem_size
        self.x[i] = x
        self.y[i] = y
        self.mem_cntr += 1

    def sample(self, rng, batch_size):
        hi = min(self.mem_cntr, self.mem_size)
        idx = rng.choice(hi, min(batch_size, hi), replace=False)
        return self.x[idx], self.y[idx]

    def resize(self, new_size):
        old_x, old_y, n = self.x, self.y, min(self.mem_cntr, self.mem_size)
        self.x = np.zeros((new_size,) + old_x.shape[1:], np.float32)
        self.y = np.zeros((new_size,) + old_y.shape[1:], np.float32)
        self.x[:n] = old_x[:n]
        self.y[:n] = old_y[:n]
        self.mem_size = new_size
        self.mem_cntr = n

    def save(self, path):
        with open(path, "wb") as fh:
            pickle.dump({"x": self.x, "y": self.y,
                         "mem_cntr": self.mem_cntr}, fh)

    def load(self, path):
        from smartcal_tpu.runtime.atomic import strict_pickle_load

        d = strict_pickle_load(path)
        self.x, self.y, self.mem_cntr = d["x"], d["y"], d["mem_cntr"]
        self.mem_size = self.x.shape[0]
