"""MLP regressor (MSP) + training buffer for metadata -> hint regression.

Parity targets: ``demixing_rl/regressor_net.py:6-28`` (RegressorNet:
M -> 32 -> 32 -> K-1 with tanh output) and
``demixing_rl/training_buffer.py:5-51`` (TrainingBuffer).
"""

import pickle

import numpy as np
from flax import linen as nn


class RegressorNet(nn.Module):
    """3-layer MLP, tanh output in action space."""

    n_outputs: int
    hidden: int = 32

    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(self.hidden)(x))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.tanh(nn.Dense(self.n_outputs)(x))


class TrainingBuffer:
    """Minimal (x, y) ring buffer with pickle persistence
    (training_buffer.py:5-51)."""

    def __init__(self, max_size, input_shape, output_shape):
        self.mem_size = max_size
        self.mem_cntr = 0
        self.x = np.zeros((max_size, input_shape), np.float32)
        self.y = np.zeros((max_size, output_shape), np.float32)

    def store(self, x, y):
        i = self.mem_cntr % self.mem_size
        self.x[i] = x
        self.y[i] = y
        self.mem_cntr += 1

    def filled(self):
        n = min(self.mem_cntr, self.mem_size)
        return self.x[:n], self.y[:n]

    def save_checkpoint(self, path="databuffer.pkl"):
        with open(path, "wb") as fh:
            pickle.dump(self.__dict__, fh)

    def load_checkpoint(self, path="databuffer.pkl"):
        from smartcal_tpu.runtime.atomic import strict_pickle_load

        self.__dict__.update(strict_pickle_load(path))
