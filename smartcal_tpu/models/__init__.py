"""Aux models: fuzzy controller, transformer classifier, regressors."""

from .fuzzy import DemixController  # noqa: F401
from .regressor import RegressorNet, TrainingBuffer  # noqa: F401
from .transformer import TransformerEncoder, XYBuffer  # noqa: F401
from .tsk import (TSKParams, load_tsk, save_tsk, train_tsk, tsk_forward,  # noqa: F401
                  tsk_init)
