"""Trapezoidal Mamdani fuzzy controller for demixing direction priority.

Parity target: ``demixing_fuzzy/demix_controller.py`` (scikit-fuzzy based).
Seven antecedents (azimuth, azimuth_target, elevation, elevation_target,
separation, log_intensity, intensity_ratio) each with low/medium/high
trapezoids, one consequent (priority), and the reference's 13 hand-written
rules (:196-222).  The RL action reparameterizes the trapezoid breakpoints
via the chained update of ``update_set_`` (:95-112) with the exact inverse
``update_action_`` (:114-125).

TPU-first design: scikit-fuzzy builds rule objects and defuzzifies on a
discretized universe per call, per direction, on host.  Here the whole
Mamdani pipeline — trapezoid membership, min/max rule firing, clipped
aggregation, centroid defuzzification — is closed-form jnp on a fixed
101-point consequent grid, so evaluating all K-1 directions is one ``vmap``
and can fuse into the env's jitted reward path.
"""

import json
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

VAR_ORDER = ("azimuth", "azimuth_target", "elevation", "elevation_target",
             "separation", "log_intensity", "intensity_ratio")
# action layout (update_limits, demix_controller.py:127-146)
ACTION_ORDER = ("azimuth", "elevation", "separation", "log_intensity",
                "intensity_ratio", "priority", "azimuth_target",
                "elevation_target")
N_ACTION = 32   # 8 sets x 4 action values (2 per low/medium pair boundary)


def default_config() -> Dict:
    """Reference default membership limits (demix_controller.py:19-95)."""
    def trio(rng, low, med, high):
        return {"range": list(rng), "low": list(low), "medium": list(med),
                "high": list(high)}

    inputs = {
        "_azimuth": trio([-180, 180, 1], [-180, -180, -65, -55],
                         [-65, -55, 55, 65], [55, 65, 180, 180]),
        "_azimuth_target": trio([-180, 180, 1], [-180, -180, -65, -55],
                                [-65, -55, 55, 65], [55, 65, 180, 180]),
        "_elevation": trio([-90, 90, 1], [-90, -90, -5, 5],
                           [-5, 5, 50, 60], [50, 60, 90, 90]),
        "_elevation_target": trio([-90, 90, 1], [-90, -90, -5, 5],
                                  [-5, 5, 50, 60], [50, 60, 90, 90]),
        "_separation": trio([0, 180, 1], [0, 0, 10, 15],
                            [10, 15, 45, 50], [45, 50, 180, 180]),
        "_log_intensity": trio([0, 100, 1], [0, 0, 1.0, 2.0],
                               [1.0, 2.0, 5.0, 10], [5.0, 10, 100, 100]),
        "_intensity_ratio": trio([0, 100, 1], [0, 0, 0.5, 1.0],
                                 [0.5, 1.0, 50, 55], [50, 55, 100, 100]),
    }
    outputs = {"_priority": trio([0, 100, 1], [0, 0, 40, 50],
                                 [40, 50, 70, 75], [70, 75, 100, 100])}
    return {"inputs": inputs, "outputs": outputs,
            "_comment": "membership limits (auto-generated)"}


def trapmf(x, abcd):
    """Trapezoidal membership (skfuzzy.trapmf semantics): 0 outside [a, d],
    1 inside [b, c], linear ramps; degenerate ramps (a==b / c==d) are
    steps."""
    a, b, c, d = abcd[..., 0], abcd[..., 1], abcd[..., 2], abcd[..., 3]
    up = jnp.where(b > a, (x - a) / jnp.where(b > a, b - a, 1.0), 1.0)
    down = jnp.where(d > c, (d - x) / jnp.where(d > c, d - c, 1.0), 1.0)
    y = jnp.minimum(jnp.minimum(up, 1.0), jnp.minimum(down, 1.0))
    y = jnp.where((x < a) | (x > d), 0.0, y)
    return jnp.clip(y, 0.0, 1.0)


def _membership_arrays(config):
    """config -> {var: (3, 4) array rows [low, medium, high]} + priority."""
    arrs = {}
    for name in VAR_ORDER:
        c = config["inputs"]["_" + name]
        arrs[name] = np.asarray([c["low"], c["medium"], c["high"]],
                                np.float32)
    p = config["outputs"]["_priority"]
    arrs["priority"] = np.asarray([p["low"], p["medium"], p["high"]],
                                  np.float32)
    return arrs


@jax.jit
def mamdani_priority(mf_stack, priority_mf, inputs):
    """Crisp priority for one direction.

    mf_stack: (7, 3, 4) trapezoids for the 7 antecedents (VAR_ORDER rows,
    [low, medium, high] columns); priority_mf: (3, 4); inputs: (7,) crisp
    values.  Rules are the reference's 13 (demix_controller.py:196-222);
    AND=min, OR=max, implication=clip, aggregation=max, centroid defuzz on a
    101-point universe.
    """
    mu = trapmf(inputs[:, None], mf_stack)           # (7, 3) memberships
    az, azt, el, elt, sep, li, ir = (mu[i] for i in range(7))
    LOW, MED, HIGH = 0, 1, 2

    r = [
        jnp.minimum(az[LOW], azt[LOW]),                              # 0 med
        jnp.minimum(az[MED], azt[MED]),                              # 1 med
        jnp.minimum(az[HIGH], azt[HIGH]),                            # 2 med
        sep[LOW],                                                    # 3 high
        el[LOW],                                                     # 4 low
        jnp.min(jnp.stack([el[LOW], sep[HIGH], li[LOW], ir[LOW]])),  # 5 low
        jnp.min(jnp.stack([el[MED], sep[MED], ir[HIGH]])),           # 6 med
        jnp.min(jnp.stack([el[HIGH], sep[MED], ir[HIGH]])),          # 7 high
        jnp.min(jnp.stack([el[HIGH], li[HIGH], ir[HIGH]])),          # 8 high
        jnp.max(jnp.stack([el[MED], sep[MED], li[MED], ir[MED]])),   # 9 med
        jnp.minimum(elt[LOW], el[HIGH]),                             # 10 high
        jnp.minimum(elt[HIGH], el[LOW]),                             # 11 low
        jnp.minimum(elt[MED], el[HIGH]),                             # 12 med
    ]
    fire_low = jnp.max(jnp.stack([r[4], r[5], r[11]]))
    fire_med = jnp.max(jnp.stack([r[0], r[1], r[2], r[6], r[9], r[12]]))
    fire_high = jnp.max(jnp.stack([r[3], r[7], r[8], r[10]]))

    u = jnp.linspace(0.0, 100.0, 101)
    agg = jnp.maximum(
        jnp.maximum(jnp.minimum(fire_low, trapmf(u, priority_mf[0])),
                    jnp.minimum(fire_med, trapmf(u, priority_mf[1]))),
        jnp.minimum(fire_high, trapmf(u, priority_mf[2])))
    total = jnp.sum(agg)
    # skfuzzy raises on all-zero aggregate; the reference catches it and
    # falls back to priority=50 (demix_controller.py:240-246)
    return jnp.where(total > 1e-9, jnp.sum(agg * u) / (total + 1e-30), 50.0)


class DemixController:
    """Reference-API wrapper (update_limits / update_action / evaluate /
    get_high_priority / print_config) over the jnp Mamdani core."""

    def __init__(self, n_action=N_ACTION):
        self.n_action = n_action
        self.config = default_config()
        assert n_action == N_ACTION

    # -- action <-> membership maps (demix_controller.py:95-125) ------------

    @staticmethod
    def _update_set(fz, action):
        hi = fz["range"][1]
        fz["low"][2] = fz["low"][1] + action[0] * (hi - fz["low"][1])
        fz["low"][3] = fz["low"][2] + action[1] * (hi - fz["low"][2])
        fz["medium"][0] = fz["low"][2]
        fz["medium"][1] = fz["low"][3]
        fz["medium"][2] = fz["medium"][1] + action[2] * (hi - fz["medium"][1])
        fz["medium"][3] = fz["medium"][2] + action[3] * (hi - fz["medium"][2])
        fz["high"][0] = fz["medium"][2]
        fz["high"][1] = fz["medium"][3]

    @staticmethod
    def _update_action(fz, action):
        hi = fz["range"][1]
        action[0] = (fz["low"][2] - fz["low"][1]) / (hi - fz["low"][1])
        action[1] = (fz["low"][3] - fz["low"][2]) / (hi - fz["low"][2])
        action[2] = ((fz["medium"][2] - fz["medium"][1])
                     / (hi - fz["medium"][1]))
        action[3] = ((fz["medium"][3] - fz["medium"][2])
                     / (hi - fz["medium"][2]))

    def update_limits(self, action):
        action = np.asarray(action)
        assert action.size == self.n_action
        ins, outs = self.config["inputs"], self.config["outputs"]
        for i, name in enumerate(ACTION_ORDER):
            grp = outs if name == "priority" else ins
            self._update_set(grp["_" + name], action[4 * i:4 * i + 4])

    def update_action(self):
        action = np.zeros(self.n_action)
        ins, outs = self.config["inputs"], self.config["outputs"]
        for i, name in enumerate(ACTION_ORDER):
            grp = outs if name == "priority" else ins
            self._update_action(grp["_" + name], action[4 * i:4 * i + 4])
        return action

    # -- evaluation ---------------------------------------------------------

    def membership_stack(self):
        arrs = _membership_arrays(self.config)
        mf = jnp.asarray(np.stack([arrs[n] for n in VAR_ORDER]))
        return mf, jnp.asarray(arrs["priority"])

    def create_controller(self):
        """No-op for API parity: the jnp core consumes the config directly
        (the reference rebuilds a skfuzzy ControlSystem here)."""

    def evaluate(self, azimuth, azimuth_target, elevation, elevation_target,
                 separation, log_intensity, intensity_ratio):
        mf, pmf = self.membership_stack()
        x = jnp.asarray([azimuth, azimuth_target, elevation,
                         elevation_target, separation, log_intensity,
                         intensity_ratio], jnp.float32)
        return float(mamdani_priority(mf, pmf, x))

    def get_high_priority(self):
        return self.config["outputs"]["_priority"]["high"][0]

    def print_config(self, filename=None):
        if filename:
            with open(filename, "w+") as fh:
                json.dump(self.config, fh)
        else:
            from smartcal_tpu import obs
            obs.echo(self.config, event="fuzzy_config")
