"""smartcal_tpu — TPU-native framework with the capabilities of
SarodYatawatta/smart-calibration.

Deep-RL (SAC/TD3/DDPG + PER + hint-constrained ADMM losses) tuning of
data-processing pipelines — elastic-net regression and radio-interferometric
calibration/demixing — built JAX/XLA/pallas/pjit-first.  See SURVEY.md at the
repo root for the reference structural map this build targets.

Subpackages
-----------
ops       numerical core: L-BFGS, autodiff/influence tools, calibration
          kernels (coherency prediction, consensus polynomials, Hessians,
          solution/residual derivatives), FFT imaging
envs      gym-style environments as pure (reset, step) function pairs
rl        SAC / TD3 / DDPG agents, replay buffers (uniform + PER), hints
models    aux models: transformer classifier, MLP regressor, TSK fuzzy,
          fuzzy demixing controller
sim       sky/observation simulators and the in-framework calibration
          backend (replaces SAGECal/excon/makems)
parallel  device meshes, distributed learner/actor runtime over collectives
data      host-side data edge: text sky/cluster/rho/solutions formats,
          FITS/MS IO gates
train     CLI drivers
"""

__version__ = "0.1.0"
