"""JSONL metrics stream + profiler hook.

The reference's observability is ``print()`` + pickled score lists
(SURVEY §5 metrics/logging); the build plan (SURVEY §7 L6) calls for
structured metrics.  Since the obs layer landed, the real implementation
is :class:`smartcal_tpu.obs.RunLog` (header line, buffered/rotating
writes, non-finite sanitization — the old per-line writer emitted bare
``NaN``/``Infinity`` tokens, which are invalid JSON); ``JsonlLogger``
stays as a thin compatibility shim with its original surface: headerless
stream, one flushed line per event, ``None`` path disables.

``profiler_trace`` wraps a code region in ``jax.profiler.trace`` when a
directory is given (view with TensorBoard / xprof), else is a no-op —
the "where does the calibration episode spend its time" hook VERDICT r1
weak #1/missing #8 asked for.
"""

from __future__ import annotations

import contextlib
from typing import Optional

from smartcal_tpu.obs import RunLog


class JsonlLogger:
    """Back-compat shim over :class:`smartcal_tpu.obs.RunLog`: headerless,
    flush-per-line (the original crash-safety contract), sanitized."""

    def __init__(self, path: Optional[str]):
        self._run = RunLog(path, header=False, flush_lines=1,
                           flush_interval=0.0)

    def log(self, event: str, **fields):
        self._run.log(event, **fields)

    def close(self):
        self._run.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


@contextlib.contextmanager
def profiler_trace(trace_dir: Optional[str]):
    """jax.profiler.trace(trace_dir) when set, no-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


def enable_compilation_cache(path: str = "/tmp/smartcal_jax_cache",
                             min_compile_secs: float = 2.0) -> bool:
    """Turn on JAX's persistent compilation cache (idempotent).

    The radio-solver programs take minutes to compile on the single-core
    CPU host (jit_solve_admm was measured at 3m24s); across pytest
    processes, sweep runs, and bench invocations the SAME programs are
    rebuilt from scratch every time because each process has a fresh
    in-memory cache.  The persistent cache keys on the serialized HLO +
    compile options, so re-runs deserialize instead.  Only compiles
    slower than ``min_compile_secs`` are persisted — trivial kernels
    would bloat the directory for no win.  Returns False (and changes
    nothing) if this jax build lacks the config knobs.

    SMARTCAL_NO_COMPILE_CACHE=1 disables (e.g. when debugging suspected
    stale-cache miscompiles).
    """
    import os

    if os.environ.get("SMARTCAL_NO_COMPILE_CACHE", "") == "1":
        return False
    import jax

    try:
        # threshold FIRST: if only the dir knob existed, setting it last
        # would leave the cache active with the default (persist
        # everything) threshold after we report False
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("SMARTCAL_COMPILE_CACHE_DIR",
                                         path))
        return True
    except (AttributeError, ValueError):
        return False
