"""JSONL metrics stream + profiler hook.

The reference's observability is ``print()`` + pickled score lists
(SURVEY §5 metrics/logging); the build plan (SURVEY §7 L6) calls for
structured metrics.  One line per event, machine-readable, crash-safe
(append + flush per line):

    {"t": <unix seconds>, "event": "episode", "score": ..., ...}

``profiler_trace`` wraps a code region in ``jax.profiler.trace`` when a
directory is given (view with TensorBoard / xprof), else is a no-op —
the "where does the calibration episode spend its time" hook VERDICT r1
weak #1/missing #8 asked for.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Optional


class JsonlLogger:
    """Append-mode JSONL metrics writer; ``None`` path disables it."""

    def __init__(self, path: Optional[str]):
        self._fh = open(path, "a") if path else None

    def log(self, event: str, **fields):
        if self._fh is None:
            return
        rec = {"t": round(time.time(), 3), "event": event}
        rec.update({k: (float(v) if hasattr(v, "item") else v)
                    for k, v in fields.items()})
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


@contextlib.contextmanager
def profiler_trace(trace_dir: Optional[str]):
    """jax.profiler.trace(trace_dir) when set, no-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


def enable_compilation_cache(path: str = "/tmp/smartcal_jax_cache",
                             min_compile_secs: float = 2.0) -> bool:
    """Turn on JAX's persistent compilation cache (idempotent).

    The radio-solver programs take minutes to compile on the single-core
    CPU host (jit_solve_admm was measured at 3m24s); across pytest
    processes, sweep runs, and bench invocations the SAME programs are
    rebuilt from scratch every time because each process has a fresh
    in-memory cache.  The persistent cache keys on the serialized HLO +
    compile options, so re-runs deserialize instead.  Only compiles
    slower than ``min_compile_secs`` are persisted — trivial kernels
    would bloat the directory for no win.  Returns False (and changes
    nothing) if this jax build lacks the config knobs.

    SMARTCAL_NO_COMPILE_CACHE=1 disables (e.g. when debugging suspected
    stale-cache miscompiles).
    """
    import os

    if os.environ.get("SMARTCAL_NO_COMPILE_CACHE", "") == "1":
        return False
    import jax

    try:
        # threshold FIRST: if only the dir knob existed, setting it last
        # would leave the cache active with the default (persist
        # everything) threshold after we report False
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("SMARTCAL_COMPILE_CACHE_DIR",
                                         path))
        return True
    except (AttributeError, ValueError):
        return False
