from .metrics import JsonlLogger, profiler_trace  # noqa: F401
