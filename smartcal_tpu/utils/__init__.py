from .metrics import (JsonlLogger, enable_compilation_cache,  # noqa: F401
                      profiler_trace)
