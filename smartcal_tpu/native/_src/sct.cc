// SCT — smartcal columnar table store (first-party native data edge).
//
// The reference's Measurement-Set I/O runs through casacore, a C++ table
// system reached via python-casacore (reference calibration/casa_io.py:1,
// generate_data.py:5-7).  This file is the framework's own native
// equivalent for the synthetic/work-file path: a single-file binary
// columnar table with named, typed, n-dimensional columns, written and
// read through a C ABI (ctypes-bound, no pybind11 in this image).
//
// Format (little-endian, version 1):
//   char   magic[4] = "SCT1"
//   u32    ncols
//   ncols x {
//     u32  name_len;  char name[name_len]
//     u32  dtype                // codes below, match numpy dtypes
//     u32  ndim                 // 0 for scalars
//     u64  dims[ndim]
//     u64  nbytes               // payload size of this column
//   }
//   column payloads, each 64-byte aligned, in header order.
//
// dtype codes: 0=float32 1=float64 2=int32 3=int64 4=complex64
//              5=complex128 6=uint8
//
// All functions return 0 (or a non-negative count) on success and a
// negative error code on failure; no exceptions cross the ABI.

#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr char kMagic[4] = {'S', 'C', 'T', '1'};
constexpr uint64_t kAlign = 64;

constexpr int kErrIO = -1;        // open/read/write failure
constexpr int kErrFormat = -2;    // bad magic / truncated header
constexpr int kErrNotFound = -3;  // no such column
constexpr int kErrSpace = -4;     // caller buffer too small
constexpr int kErrArg = -5;       // bad argument

size_t dtype_size(uint32_t code) {
  switch (code) {
    case 0: return 4;   // float32
    case 1: return 8;   // float64
    case 2: return 4;   // int32
    case 3: return 8;   // int64
    case 4: return 8;   // complex64
    case 5: return 16;  // complex128
    case 6: return 1;   // uint8
    default: return 0;
  }
}

struct ColMeta {
  std::string name;
  uint32_t dtype = 0;
  std::vector<uint64_t> dims;
  uint64_t nbytes = 0;
  uint64_t offset = 0;  // absolute file offset of the payload
};

struct FileCloser {
  FILE* f;
  ~FileCloser() { if (f) std::fclose(f); }
};

struct SctHandle {
  FILE* f = nullptr;
  std::vector<ColMeta> cols;
};

bool read_exact(FILE* f, void* p, size_t n) {
  return std::fread(p, 1, n, f) == n;
}

bool write_exact(FILE* f, const void* p, size_t n) {
  return std::fwrite(p, 1, n, f) == n;
}

// Parse the header; on success positions *f at the end of the header and
// fills cols (offsets resolved).  Returns 0 or a negative error.
int parse_header(FILE* f, std::vector<ColMeta>* cols) {
  char magic[4];
  uint32_t ncols = 0;
  if (!read_exact(f, magic, 4)) return kErrFormat;
  if (std::memcmp(magic, kMagic, 4) != 0) return kErrFormat;
  if (!read_exact(f, &ncols, 4)) return kErrFormat;
  if (ncols > 1u << 20) return kErrFormat;
  cols->clear();
  cols->reserve(ncols);
  for (uint32_t i = 0; i < ncols; ++i) {
    ColMeta c;
    uint32_t name_len = 0;
    if (!read_exact(f, &name_len, 4)) return kErrFormat;
    if (name_len > 4096) return kErrFormat;
    c.name.resize(name_len);
    if (name_len && !read_exact(f, &c.name[0], name_len)) return kErrFormat;
    uint32_t ndim = 0;
    if (!read_exact(f, &c.dtype, 4)) return kErrFormat;
    if (!read_exact(f, &ndim, 4)) return kErrFormat;
    if (ndim > 16) return kErrFormat;
    c.dims.resize(ndim);
    if (ndim && !read_exact(f, c.dims.data(), 8 * ndim)) return kErrFormat;
    if (!read_exact(f, &c.nbytes, 8)) return kErrFormat;
    cols->push_back(std::move(c));
  }
  // resolve aligned payload offsets relative to the header end
  long hdr_end = std::ftell(f);
  if (hdr_end < 0) return kErrIO;
  uint64_t off = static_cast<uint64_t>(hdr_end);
  for (auto& c : *cols) {
    off = (off + kAlign - 1) / kAlign * kAlign;
    c.offset = off;
    off += c.nbytes;
  }
  return 0;
}

}  // namespace

extern "C" {

// Write a table.  dims_flat packs each column's dims consecutively
// (sum(ndims[i]) entries).  Payload sizes are derived from dims * dtype.
int sct_write(const char* path, int ncols, const char** names,
              const int* dtypes, const int* ndims,
              const int64_t* dims_flat, const void** data) {
  if (!path || ncols < 0) return kErrArg;
  // unique temp name: concurrent writers to the same table must not
  // truncate each other's staging file (the rename stays atomic)
  static std::atomic<uint64_t> seq{0};
  std::string tmp = std::string(path) + ".tmp." +
                    std::to_string(static_cast<long>(getpid())) + "." +
                    std::to_string(seq.fetch_add(1));
  // reject anything the reader's header limits would refuse BEFORE
  // creating the staging file — a successful write must stay readable
  for (int i = 0; i < ncols; ++i) {
    if (dtype_size(static_cast<uint32_t>(dtypes[i])) == 0) return kErrArg;
    if (std::strlen(names[i]) > 4096) return kErrArg;
    if (ndims[i] < 0 || ndims[i] > 16) return kErrArg;
  }
  FILE* f = std::fopen(tmp.c_str(), "wb");
  if (!f) return kErrIO;
  FileCloser closer{f};
  struct TmpCleaner {       // unlink the staging file unless committed
    const std::string* name;
    ~TmpCleaner() { if (name) std::remove(name->c_str()); }
  } tmp_cleaner{&tmp};

  if (!write_exact(f, kMagic, 4)) return kErrIO;
  uint32_t nc = static_cast<uint32_t>(ncols);
  if (!write_exact(f, &nc, 4)) return kErrIO;

  std::vector<uint64_t> sizes(ncols);
  const int64_t* dp = dims_flat;
  for (int i = 0; i < ncols; ++i) {
    size_t esz = dtype_size(static_cast<uint32_t>(dtypes[i]));
    uint64_t n = 1;
    uint32_t name_len = static_cast<uint32_t>(std::strlen(names[i]));
    uint32_t dt = static_cast<uint32_t>(dtypes[i]);
    uint32_t nd = static_cast<uint32_t>(ndims[i]);
    if (!write_exact(f, &name_len, 4)) return kErrIO;
    if (!write_exact(f, names[i], name_len)) return kErrIO;
    if (!write_exact(f, &dt, 4)) return kErrIO;
    if (!write_exact(f, &nd, 4)) return kErrIO;
    for (int d = 0; d < ndims[i]; ++d) {
      uint64_t dim = static_cast<uint64_t>(dp[d]);
      if (!write_exact(f, &dim, 8)) return kErrIO;
      n *= dim;
    }
    dp += ndims[i];
    sizes[i] = n * esz;
    if (!write_exact(f, &sizes[i], 8)) return kErrIO;
  }

  static const char pad[kAlign] = {0};
  for (int i = 0; i < ncols; ++i) {
    long pos = std::ftell(f);
    if (pos < 0) return kErrIO;
    uint64_t aligned =
        (static_cast<uint64_t>(pos) + kAlign - 1) / kAlign * kAlign;
    if (!write_exact(f, pad, aligned - pos)) return kErrIO;
    if (sizes[i] && !write_exact(f, data[i], sizes[i])) return kErrIO;
  }
  // flush + fsync BEFORE the rename: otherwise a crash can commit the
  // rename metadata while the data blocks are still unwritten, replacing
  // a good table with a truncated one
  if (std::fflush(f) != 0) return kErrIO;
  if (fsync(fileno(f)) != 0) return kErrIO;
  std::fclose(f);
  closer.f = nullptr;
  if (std::rename(tmp.c_str(), path) != 0) return kErrIO;  // atomic replace
  tmp_cleaner.name = nullptr;                              // committed
  return 0;
}

// ---- handle-based reader: the header is parsed ONCE per open -------------

void* sct_open(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  auto* h = new SctHandle();
  h->f = f;
  if (parse_header(f, &h->cols) != 0) {
    std::fclose(f);
    delete h;
    return nullptr;
  }
  return h;
}

void sct_close(void* handle) {
  auto* h = static_cast<SctHandle*>(handle);
  if (!h) return;
  if (h->f) std::fclose(h->f);
  delete h;
}

int sct_h_ncols(void* handle) {
  return static_cast<int>(static_cast<SctHandle*>(handle)->cols.size());
}

// Index of a named column, or kErrNotFound.
int sct_h_find(void* handle, const char* name) {
  auto* h = static_cast<SctHandle*>(handle);
  for (size_t i = 0; i < h->cols.size(); ++i)
    if (h->cols[i].name == name) return static_cast<int>(i);
  return kErrNotFound;
}

// Metadata of column `index`: name copied into name_out (NUL-terminated,
// capacity name_cap), dims into dims_out (capacity 16).  Returns ndim.
int sct_h_col_meta(void* handle, int index, char* name_out, int name_cap,
                   int* dtype, int64_t* dims_out) {
  auto* h = static_cast<SctHandle*>(handle);
  if (index < 0 || index >= static_cast<int>(h->cols.size())) return kErrArg;
  const ColMeta& c = h->cols[index];
  if (static_cast<int>(c.name.size()) + 1 > name_cap) return kErrSpace;
  std::memcpy(name_out, c.name.c_str(), c.name.size() + 1);
  *dtype = static_cast<int>(c.dtype);
  for (size_t d = 0; d < c.dims.size(); ++d)
    dims_out[d] = static_cast<int64_t>(c.dims[d]);
  return static_cast<int>(c.dims.size());
}

// Read column `index` into out (capacity out_bytes).  Returns bytes read.
int64_t sct_h_read_col(void* handle, int index, void* out,
                       int64_t out_bytes) {
  auto* h = static_cast<SctHandle*>(handle);
  if (index < 0 || index >= static_cast<int>(h->cols.size())) return kErrArg;
  const ColMeta& c = h->cols[index];
  if (static_cast<int64_t>(c.nbytes) > out_bytes) return kErrSpace;
  if (std::fseek(h->f, static_cast<long>(c.offset), SEEK_SET) != 0)
    return kErrIO;
  if (c.nbytes && !read_exact(h->f, out, c.nbytes)) return kErrIO;
  return static_cast<int64_t>(c.nbytes);
}

}  // extern "C"
