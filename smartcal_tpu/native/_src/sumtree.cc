// Native binary sum tree for prioritized experience replay.
//
// The reference keeps PER in a host-side python SumTree with O(log n)
// serial add/update/get_leaf walks (reference elasticnet/enet_sac.py:82-200).
// The TPU build's default PER lives in HBM as a vectorised prefix-sum
// search (smartcal_tpu/rl/replay.py); SURVEY.md §7 ("PER on TPU") calls for
// measuring BOTH designs — this file is the host-side tree, in C++ so the
// per-sample pointer chase costs nanoseconds instead of python-interpreter
// microseconds.  Bound via ctypes (no pybind11 in this image).
//
// Layout: classic implicit heap over a power-of-two leaf count `cap`:
// tree[1] is the root (total priority), leaves occupy tree[cap .. 2cap-1];
// leaf i of the ring buffer is tree[cap + i].

#include <cstdint>
#include <vector>

namespace {

struct SumTree {
  int64_t cap;                // leaves, power of two
  std::vector<double> tree;   // 2*cap entries, index 0 unused (sums)
  std::vector<double> maxt;   // max overlay, same layout — O(log n)
                              // max-priority queries for the PER
                              // max-priority store rule, which runs on
                              // EVERY default-priority store
  int64_t cursor;             // next leaf to write (ring)
  int64_t filled;             // number of leaves ever written (<= cap)
};

void propagate(SumTree* t, int64_t node) {
  for (node >>= 1; node >= 1; node >>= 1) {
    t->tree[node] = t->tree[2 * node] + t->tree[2 * node + 1];
    double l = t->maxt[2 * node], r = t->maxt[2 * node + 1];
    t->maxt[node] = l > r ? l : r;
  }
}

}  // namespace

extern "C" {

// capacity is rounded UP to the next power of two (the reference asserts
// power-of-two capacity instead, enet_sac.py:90-93).
void* st_create(int64_t capacity) {
  if (capacity <= 0) return nullptr;
  int64_t cap = 1;
  while (cap < capacity) cap <<= 1;
  auto* t = new SumTree();
  t->cap = cap;
  t->tree.assign(2 * cap, 0.0);
  t->maxt.assign(2 * cap, 0.0);
  t->cursor = 0;
  t->filled = 0;
  return t;
}

void st_free(void* h) { delete static_cast<SumTree*>(h); }

int64_t st_capacity(void* h) { return static_cast<SumTree*>(h)->cap; }
int64_t st_filled(void* h) { return static_cast<SumTree*>(h)->filled; }
int64_t st_cursor(void* h) { return static_cast<SumTree*>(h)->cursor; }

double st_total(void* h) { return static_cast<SumTree*>(h)->tree[1]; }

// Max leaf priority (PER max-priority init, enet_sac.py:237-241); O(1)
// off the max overlay.  Unfilled leaves hold 0 and priorities are
// non-negative, so the overlay root IS the filled-prefix max; 0 when empty.
double st_max_priority(void* h) {
  return static_cast<SumTree*>(h)->maxt[1];
}

// Min non-zero leaf probability numerator (some PER variants need it for
// the max-IS-weight bound).  0 when empty.  O(n) linear scan — NOT on any
// per-store path (unused by NativePER; exposed for completeness).
double st_min_priority(void* h) {
  auto* t = static_cast<SumTree*>(h);
  double m = 0.0;
  bool any = false;
  for (int64_t i = 0; i < t->filled; ++i) {
    double v = t->tree[t->cap + i];
    if (v > 0.0 && (!any || v < m)) { m = v; any = true; }
  }
  return any ? m : 0.0;
}

// Append at the ring cursor (SumTree.add, enet_sac.py:120-131); returns the
// leaf index written.
int64_t st_add(void* h, double priority) {
  auto* t = static_cast<SumTree*>(h);
  int64_t leaf = t->cursor;
  t->tree[t->cap + leaf] = priority;
  t->maxt[t->cap + leaf] = priority;
  propagate(t, t->cap + leaf);
  t->cursor = (t->cursor + 1) % t->cap;
  if (t->filled < t->cap) ++t->filled;
  return leaf;
}

void st_update(void* h, int64_t leaf, double priority) {
  auto* t = static_cast<SumTree*>(h);
  if (leaf < 0 || leaf >= t->cap) return;
  t->tree[t->cap + leaf] = priority;
  t->maxt[t->cap + leaf] = priority;
  propagate(t, t->cap + leaf);
}

void st_update_batch(void* h, int64_t n, const int64_t* leaves,
                     const double* priorities) {
  for (int64_t i = 0; i < n; ++i) st_update(h, leaves[i], priorities[i]);
}

// Root-to-leaf walk for cumulative value v (SumTree.get_leaf,
// enet_sac.py:164-196).  Returns the leaf index; *priority_out gets its
// priority.
int64_t st_get_leaf(void* h, double v, double* priority_out) {
  auto* t = static_cast<SumTree*>(h);
  int64_t node = 1;
  while (node < t->cap) {
    int64_t left = 2 * node;
    if (v <= t->tree[left]) {
      node = left;
    } else {
      v -= t->tree[left];
      node = left + 1;
    }
  }
  if (priority_out) *priority_out = t->tree[node];
  return node - t->cap;
}

// Stratified sampling (PER.sample_buffer, enet_sac.py:270-312): segment i
// draws v = (i + uniforms[i]) * total / batch and walks the tree.  The
// caller supplies the uniforms so the python side keeps RNG control.
void st_sample_stratified(void* h, int64_t batch, const double* uniforms,
                          int64_t* idx_out, double* priority_out) {
  auto* t = static_cast<SumTree*>(h);
  double seg = t->tree[1] / static_cast<double>(batch);
  for (int64_t i = 0; i < batch; ++i) {
    double v = (static_cast<double>(i) + uniforms[i]) * seg;
    idx_out[i] = st_get_leaf(h, v, &priority_out[i]);
  }
}

// Checkpoint support: copy all leaves out / load leaves (rebuilding the
// internal nodes) and restore the ring state.
void st_get_leaves(void* h, double* out) {
  auto* t = static_cast<SumTree*>(h);
  for (int64_t i = 0; i < t->cap; ++i) out[i] = t->tree[t->cap + i];
}

void st_set_state(void* h, const double* leaves, int64_t cursor,
                  int64_t filled) {
  auto* t = static_cast<SumTree*>(h);
  for (int64_t i = 0; i < t->cap; ++i) {
    t->tree[t->cap + i] = leaves[i];
    t->maxt[t->cap + i] = leaves[i];
  }
  for (int64_t i = t->cap - 1; i >= 1; --i) {
    t->tree[i] = t->tree[2 * i] + t->tree[2 * i + 1];
    double l = t->maxt[2 * i], r = t->maxt[2 * i + 1];
    t->maxt[i] = l > r ? l : r;
  }
  t->cursor = cursor % t->cap;
  t->filled = filled < t->cap ? filled : t->cap;
}

}  // extern "C"
