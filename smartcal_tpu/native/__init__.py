"""First-party native (C++) runtime components, bound via ctypes.

The reference's native layer is external C++ reached through bindings —
casacore tables for MS I/O (reference calibration/casa_io.py:1), plus
CUDA/MPI binaries for compute.  The compute path here is JAX/XLA/Pallas;
this package holds the framework's own native *runtime* pieces:

* ``sct.cc``  — single-file binary columnar table store (the casacore-table
  role for synthetic/work MS data; used by :mod:`smartcal_tpu.cal.ms_io`).
* ``sumtree.cc`` — host-side O(log n) sum tree for prioritized replay
  (the reference SumTree, enet_sac.py:82-200), the counterpart the
  HBM prefix-sum PER in :mod:`smartcal_tpu.rl.replay` is measured against
  (SURVEY.md §7 "PER on TPU ... measure both").

The shared library is compiled on demand with g++ (no pybind11 in this
image; plain C ABI + ctypes).  Everything degrades gracefully: if no
compiler is available, ``lib()`` returns None and callers fall back to
their pure-python/numpy paths.
"""

from __future__ import annotations

import ctypes as ct
import os
import subprocess
import tempfile
import threading
from typing import Optional

import numpy as np

_SRC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_src")
_SOURCES = ("sct.cc", "sumtree.cc")
_LIB_BASENAME = "libsmartcal_native.so"

_lock = threading.Lock()
_lib: Optional[ct.CDLL] = None
_lib_tried = False

# numpy dtype <-> SCT dtype code (sct.cc header)
DTYPE_CODES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.complex64): 4,
    np.dtype(np.complex128): 5,
    np.dtype(np.uint8): 6,
}
CODE_DTYPES = {v: k for k, v in DTYPE_CODES.items()}


def _build_dir() -> str:
    d = os.environ.get("SMARTCAL_NATIVE_BUILD_DIR")
    if not d:
        d = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")
    os.makedirs(d, exist_ok=True)
    return d


def _newest_source_mtime() -> float:
    return max(os.path.getmtime(os.path.join(_SRC_DIR, s)) for s in _SOURCES)


def build(force: bool = False) -> Optional[str]:
    """Compile the shared library if needed; returns its path or None.

    The build is a single g++ invocation writing to a temp file then
    atomically renamed, so concurrent importers race benignly.
    """
    out = os.path.join(_build_dir(), _LIB_BASENAME)
    if (not force and os.path.exists(out)
            and os.path.getmtime(out) >= _newest_source_mtime()):
        return out
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_build_dir())
    os.close(fd)
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp] + srcs
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            os.unlink(tmp)
            return None
        os.replace(tmp, out)
        return out
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _bind(path: str) -> ct.CDLL:
    lib = ct.CDLL(path)
    c_i64 = ct.c_int64
    lib.sct_write.restype = ct.c_int
    lib.sct_write.argtypes = [
        ct.c_char_p, ct.c_int, ct.POINTER(ct.c_char_p),
        ct.POINTER(ct.c_int), ct.POINTER(ct.c_int), ct.POINTER(c_i64),
        ct.POINTER(ct.c_void_p)]
    lib.sct_open.restype = ct.c_void_p
    lib.sct_open.argtypes = [ct.c_char_p]
    lib.sct_close.restype = None
    lib.sct_close.argtypes = [ct.c_void_p]
    lib.sct_h_ncols.restype = ct.c_int
    lib.sct_h_ncols.argtypes = [ct.c_void_p]
    lib.sct_h_find.restype = ct.c_int
    lib.sct_h_find.argtypes = [ct.c_void_p, ct.c_char_p]
    lib.sct_h_col_meta.restype = ct.c_int
    lib.sct_h_col_meta.argtypes = [
        ct.c_void_p, ct.c_int, ct.c_char_p, ct.c_int,
        ct.POINTER(ct.c_int), ct.POINTER(c_i64)]
    lib.sct_h_read_col.restype = c_i64
    lib.sct_h_read_col.argtypes = [ct.c_void_p, ct.c_int, ct.c_void_p,
                                   c_i64]
    lib.st_create.restype = ct.c_void_p
    lib.st_create.argtypes = [c_i64]
    lib.st_free.argtypes = [ct.c_void_p]
    for name in ("st_capacity", "st_filled", "st_cursor"):
        fn = getattr(lib, name)
        fn.restype = c_i64
        fn.argtypes = [ct.c_void_p]
    for name in ("st_total", "st_max_priority", "st_min_priority"):
        fn = getattr(lib, name)
        fn.restype = ct.c_double
        fn.argtypes = [ct.c_void_p]
    lib.st_add.restype = c_i64
    lib.st_add.argtypes = [ct.c_void_p, ct.c_double]
    lib.st_update.restype = None
    lib.st_update.argtypes = [ct.c_void_p, c_i64, ct.c_double]
    lib.st_update_batch.restype = None
    lib.st_update_batch.argtypes = [ct.c_void_p, c_i64,
                                    ct.POINTER(c_i64), ct.POINTER(ct.c_double)]
    lib.st_get_leaf.restype = c_i64
    lib.st_get_leaf.argtypes = [ct.c_void_p, ct.c_double,
                                ct.POINTER(ct.c_double)]
    lib.st_sample_stratified.restype = None
    lib.st_sample_stratified.argtypes = [
        ct.c_void_p, c_i64, ct.POINTER(ct.c_double), ct.POINTER(c_i64),
        ct.POINTER(ct.c_double)]
    lib.st_get_leaves.restype = None
    lib.st_get_leaves.argtypes = [ct.c_void_p, ct.POINTER(ct.c_double)]
    lib.st_set_state.restype = None
    lib.st_set_state.argtypes = [ct.c_void_p, ct.POINTER(ct.c_double),
                                 c_i64, c_i64]
    return lib


def lib() -> Optional[ct.CDLL]:
    """The loaded native library, building it on first use; None if the
    toolchain is unavailable (callers must fall back)."""
    global _lib, _lib_tried
    with _lock:
        if _lib is None and not _lib_tried:
            _lib_tried = True
            if os.environ.get("SMARTCAL_DISABLE_NATIVE"):
                return None
            path = build()
            if path is not None:
                try:
                    _lib = _bind(path)
                except OSError:
                    _lib = None
        return _lib


def available() -> bool:
    return lib() is not None


# ---------------------------------------------------------------------------
# SCT store: numpy dict <-> single binary file
# ---------------------------------------------------------------------------

def sct_write(path: str, columns: dict) -> None:
    """Write ``{name: ndarray}`` as one SCT file (atomic replace)."""
    L = lib()
    if L is None:
        raise RuntimeError("native library unavailable")
    names, codes, ndims, dims, ptrs, keep = [], [], [], [], [], []
    for name, arr in columns.items():
        # NOT ascontiguousarray: it promotes 0-d scalars to shape (1,)
        a = np.asarray(arr)
        if not a.flags["C_CONTIGUOUS"]:
            a = np.ascontiguousarray(a)
        if a.dtype == np.bool_:
            a = a.astype(np.uint8)
        if a.dtype not in DTYPE_CODES:
            raise TypeError(f"unsupported dtype {a.dtype} for column {name}")
        keep.append(a)                       # hold buffers until the call
        names.append(name.encode())
        codes.append(DTYPE_CODES[a.dtype])
        ndims.append(a.ndim)
        dims.extend(int(d) for d in a.shape)
        ptrs.append(a.ctypes.data_as(ct.c_void_p))
    n = len(names)
    rc = L.sct_write(
        path.encode(), n,
        (ct.c_char_p * n)(*names),
        (ct.c_int * n)(*codes),
        (ct.c_int * n)(*ndims),
        (ct.c_int64 * max(1, len(dims)))(*(dims or [0])),
        (ct.c_void_p * n)(*[ct.cast(p, ct.c_void_p) for p in ptrs]))
    if rc != 0:
        raise IOError(f"sct_write({path}) failed: rc={rc}")


class _SctReader:
    """RAII handle over one open SCT file; the header parses once."""

    def __init__(self, path: str):
        L = lib()
        if L is None:
            raise RuntimeError("native library unavailable")
        self._L = L
        self.path = path
        self._h = L.sct_open(path.encode())
        if not self._h:
            raise IOError(f"sct_open({path}): cannot open / bad header")

    def close(self):
        if getattr(self, "_h", None):
            self._L.sct_close(self._h)
            self._h = None

    def __del__(self):
        self.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def ncols(self) -> int:
        return self._L.sct_h_ncols(self._h)

    def col(self, index: int) -> np.ndarray:
        """(name, array) of column `index`."""
        name_buf = ct.create_string_buffer(4097)
        dims_buf = (ct.c_int64 * 16)()
        dtype_out = ct.c_int(0)
        ndim = self._L.sct_h_col_meta(self._h, index, name_buf, 4097,
                                      ct.byref(dtype_out), dims_buf)
        if ndim < 0:
            raise IOError(f"sct_h_col_meta({self.path}, {index}) rc={ndim}")
        shape = tuple(int(dims_buf[d]) for d in range(ndim))
        arr = np.empty(shape, CODE_DTYPES[int(dtype_out.value)])
        got = self._L.sct_h_read_col(self._h, index,
                                     arr.ctypes.data_as(ct.c_void_p),
                                     ct.c_int64(arr.nbytes))
        if got != arr.nbytes:
            raise IOError(f"sct_h_read_col({self.path}, {index}) rc={got}")
        return name_buf.value.decode(), arr

    def read_one(self, name: str) -> np.ndarray:
        """One named column's payload — nothing else is read."""
        idx = self._L.sct_h_find(self._h, name.encode())
        if idx < 0:
            raise KeyError(f"column {name} not in {self.path}")
        return self.col(idx)[1]


def _py_parse_header(f):
    """Pure-python mirror of sct.cc parse_header (same field order, limits,
    and 64-byte payload alignment): [(name, dtype, shape, offset, nbytes)].
    Keeps SCT stores READABLE on hosts without a C++ toolchain (writes fall
    back to npz there, but data written elsewhere must still open).  All
    corruption surfaces as IOError, like the native path."""
    import struct

    def read_exact(n):
        buf = f.read(n)
        if len(buf) != n:
            raise IOError("truncated SCT header")
        return buf

    if f.read(4) != b"SCT1":
        raise IOError("bad SCT magic")
    (ncols,) = struct.unpack("<I", read_exact(4))
    if ncols > 1 << 20:
        raise IOError(f"bad SCT header: ncols={ncols}")
    cols = []
    for _ in range(ncols):
        (name_len,) = struct.unpack("<I", read_exact(4))
        if name_len > 4096:
            raise IOError(f"bad SCT header: name_len={name_len}")
        try:
            name = read_exact(name_len).decode()
        except UnicodeDecodeError as e:
            raise IOError(f"bad SCT header: undecodable name ({e})")
        dtype_code, ndim = struct.unpack("<II", read_exact(8))
        if ndim > 16:
            raise IOError(f"bad SCT header: ndim={ndim}")
        if dtype_code not in CODE_DTYPES:
            raise IOError(f"bad SCT header: dtype code {dtype_code}")
        dims = (struct.unpack(f"<{ndim}Q", read_exact(8 * ndim))
                if ndim else ())
        (nbytes,) = struct.unpack("<Q", read_exact(8))
        dtype = CODE_DTYPES[dtype_code]
        itemsize = np.dtype(dtype).itemsize
        count = 1
        for d in dims:
            count *= d
        if nbytes % itemsize or count * itemsize != nbytes:
            raise IOError(
                f"bad SCT header: column {name} dims {dims} x itemsize "
                f"{itemsize} disagree with nbytes={nbytes}")
        cols.append([name, dtype, tuple(dims), 0, nbytes])
    off = f.tell()
    for c in cols:
        off = (off + 63) // 64 * 64
        c[3] = off
        off += c[4]
    return cols


def _py_read(path: str, only: Optional[str] = None):
    out = {}
    with open(path, "rb") as f:
        for name, dtype, shape, offset, nbytes in _py_parse_header(f):
            if only is not None and name != only:
                continue
            f.seek(offset)
            buf = f.read(nbytes)
            if len(buf) != nbytes:
                raise IOError(f"truncated SCT column {name} in {path}")
            out[name] = np.frombuffer(buf, dtype).reshape(shape).copy()
    if only is not None:
        if only not in out:
            raise KeyError(f"column {only} not in {path}")
        return out[only]
    return out


def sct_read(path: str) -> dict:
    """Read an SCT file back into ``{name: ndarray}`` (native reader when
    available, pure-python otherwise — the format must never need g++)."""
    if lib() is None:
        return _py_read(path)
    with _SctReader(path) as r:
        return dict(r.col(i) for i in range(r.ncols))


def sct_read_one(path: str, name: str) -> np.ndarray:
    """Read a single named column without touching the other payloads."""
    if lib() is None:
        return _py_read(path, only=name)
    with _SctReader(path) as r:
        return r.read_one(name)


# ---------------------------------------------------------------------------
# Native sum tree handle (thin RAII wrapper; PER logic lives in
# smartcal_tpu.rl.replay_native)
# ---------------------------------------------------------------------------

class SumTree:
    """ctypes handle to the C++ sum tree; capacity rounds up to 2^k."""

    def __init__(self, capacity: int):
        L = lib()
        if L is None:
            raise RuntimeError("native library unavailable")
        self._L = L
        self._h = L.st_create(int(capacity))
        if not self._h:
            raise MemoryError("st_create failed")

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._L.st_free(h)
            self._h = None

    @property
    def capacity(self) -> int:
        return int(self._L.st_capacity(self._h))

    @property
    def filled(self) -> int:
        return int(self._L.st_filled(self._h))

    @property
    def cursor(self) -> int:
        return int(self._L.st_cursor(self._h))

    def total(self) -> float:
        return float(self._L.st_total(self._h))

    def max_priority(self) -> float:
        return float(self._L.st_max_priority(self._h))

    def add(self, priority: float) -> int:
        return int(self._L.st_add(self._h, float(priority)))

    def update(self, leaf: int, priority: float) -> None:
        self._L.st_update(self._h, int(leaf), float(priority))

    def update_batch(self, leaves, priorities) -> None:
        leaves = np.ascontiguousarray(leaves, np.int64)
        priorities = np.ascontiguousarray(priorities, np.float64)
        self._L.st_update_batch(
            self._h, leaves.size,
            leaves.ctypes.data_as(ct.POINTER(ct.c_int64)),
            priorities.ctypes.data_as(ct.POINTER(ct.c_double)))

    def get_leaf(self, v: float):
        p = ct.c_double(0.0)
        leaf = int(self._L.st_get_leaf(self._h, float(v), ct.byref(p)))
        return leaf, float(p.value)

    def sample_stratified(self, batch: int, uniforms):
        uniforms = np.ascontiguousarray(uniforms, np.float64)
        assert uniforms.size == batch
        idx = np.empty(batch, np.int64)
        pri = np.empty(batch, np.float64)
        self._L.st_sample_stratified(
            self._h, batch,
            uniforms.ctypes.data_as(ct.POINTER(ct.c_double)),
            idx.ctypes.data_as(ct.POINTER(ct.c_int64)),
            pri.ctypes.data_as(ct.POINTER(ct.c_double)))
        return idx, pri

    def leaves(self) -> np.ndarray:
        out = np.empty(self.capacity, np.float64)
        self._L.st_get_leaves(self._h,
                              out.ctypes.data_as(ct.POINTER(ct.c_double)))
        return out

    def set_state(self, leaves, cursor: int, filled: int) -> None:
        leaves = np.ascontiguousarray(leaves, np.float64)
        assert leaves.size == self.capacity
        self._L.st_set_state(
            self._h, leaves.ctypes.data_as(ct.POINTER(ct.c_double)),
            int(cursor), int(filled))
